"""Packaging for quiver_tpu (reference setup.py builds the CUDA extension;
here the native piece is the plain-C-ABI host engine, compiled by a custom
build step with no pybind11/torch involvement)."""

import os
import subprocess

from setuptools import find_packages, setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        csrc = os.path.join(os.path.dirname(os.path.abspath(__file__)), "quiver_tpu", "csrc")
        if os.path.exists(os.path.join(csrc, "Makefile")):
            try:
                subprocess.run(["make", "-C", csrc], check=True)
            except Exception as e:  # native lib is optional (numpy fallback)
                print(f"warning: native build failed ({e}); numpy fallbacks will be used")
        super().run()


setup(
    name="quiver-tpu",
    version="0.1.0",
    description="TPU-native graph-learning data engine (torch-quiver capabilities on JAX/XLA/Pallas)",
    packages=find_packages(include=["quiver_tpu", "quiver_tpu.*", "quiver"]),
    package_data={"quiver_tpu": ["csrc/*.so", "csrc/*.cpp", "csrc/Makefile"]},
    python_requires=">=3.10",
    install_requires=["jax", "flax", "optax", "numpy"],
    cmdclass={"build_py": BuildWithNative},
)

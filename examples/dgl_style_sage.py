"""DGL-style GraphSAGE training — the reference's DGL front end, TPU-native.

Mirrors /root/reference/examples/dgl/ogbn_products_sage_quiver.py: a SAGE
model that consumes sampling output as DGL blocks/MFGs
(``h_dst = h[:block.num_dst_nodes()]``, layers called as
``layer(block, (h, h_dst))``), with quiver supplying the sampler and the
cached feature table. The adapter surface lives in `quiver_tpu.dgl_compat`
(see its module docstring for the full DGL -> quiver_tpu mapping table).

Run: JAX_PLATFORMS=cpu python examples/dgl_style_sage.py --epochs 5
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=20_000)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--sizes", default="10,5")
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--steps-per-epoch", type=int, default=0)
    ap.add_argument("--cache", default="4M")
    ap.add_argument("--lr", type=float, default=0.003)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    import quiver_tpu as quiver
    from quiver_tpu.datasets import synthetic_community
    from quiver_tpu.dgl_compat import Block, DGLStyleSAGE, to_blocks

    ei, feat, labels, train_idx = synthetic_community(
        args.nodes, communities=args.classes, avg_deg=12, dim=args.dim,
        feature_signal=1.0, seed=0,
    )
    topo = quiver.CSRTopo(edge_index=ei)
    sizes = [int(s) for s in args.sizes.split(",")]
    # the quiver pieces, exactly as the reference DGL example uses them:
    # sampler feeds blocks, Feature serves the gathered rows
    sampler = quiver.pyg.GraphSageSampler(topo, sizes=sizes, mode="TPU", seed=0)
    f = quiver.Feature(
        rank=0, device_list=[0], device_cache_size=args.cache, csr_topo=topo
    )
    f.from_cpu_tensor(feat)

    model = DGLStyleSAGE(
        hidden_dim=args.hidden, out_dim=args.classes, num_layers=len(sizes),
        dropout=0.5,
    )
    tx = optax.adam(args.lr)

    rng = np.random.default_rng(0)
    ds0 = sampler.sample_dense(rng.choice(train_idx, args.batch_size))
    _, _, blocks0 = to_blocks(ds0)
    x0 = f[ds0.n_id]
    params = model.init(
        {"params": jax.random.key(0), "dropout": jax.random.key(1)},
        blocks0, x0, train=True,
    )
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, key, x, adjs, y):
        # Block wrappers carry only static metadata beyond the adjs, so the
        # jitted step takes the adj pytrees and rebuilds blocks in-trace
        def obj(p):
            blocks, src_w = [], x.shape[0]
            for adj in adjs:
                blocks.append(Block(adj, src_w))
                src_w = adj.w_dst
            logits = model.apply(
                p, blocks, x, train=True, rngs={"dropout": key}
            )
            ll = jax.nn.log_softmax(logits)
            return -jnp.take_along_axis(ll, y[:, None], axis=1).mean()

        loss, g = jax.value_and_grad(obj)(params)
        u, opt_state = tx.update(g, opt_state, params)
        return optax.apply_updates(params, u), opt_state, loss

    steps = args.steps_per_epoch or max(len(train_idx) // args.batch_size, 1)
    for epoch in range(args.epochs):
        t0 = time.time()
        for i in range(steps):
            seeds = rng.choice(train_idx, args.batch_size)
            ds = sampler.sample_dense(seeds)
            input_nodes, output_nodes, _ = to_blocks(ds)
            x = f[input_nodes]
            y = jnp.asarray(
                labels[np.asarray(output_nodes)].astype(np.int32)
            )
            params, opt_state, loss = step(
                params, opt_state, jax.random.key(epoch * 10_000 + i),
                x, ds.adjs, y,
            )
        print(f"epoch {epoch}: {time.time()-t0:.2f}s  loss={float(loss):.4f}")

    # eval: sampled inference on held-out nodes through the same blocks path
    test = np.setdiff1d(np.arange(args.nodes), train_idx)
    test = rng.choice(test, min(2048, len(test)), replace=False)
    correct = total = 0
    for beg in range(0, len(test), args.batch_size):
        seeds = test[beg : beg + args.batch_size]
        if len(seeds) < args.batch_size:  # keep the jitted shape
            seeds = np.pad(seeds, (0, args.batch_size - len(seeds)), mode="edge")
        ds = sampler.sample_dense(seeds)
        input_nodes, output_nodes, blocks = to_blocks(ds)
        logits = model.apply(params, blocks, f[input_nodes], train=False)
        pred = np.asarray(logits.argmax(axis=1))
        ok = pred == labels[np.asarray(output_nodes)]
        take = min(len(test) - beg, args.batch_size)
        correct += int(ok[:take].sum())
        total += take
    print(f"test acc: {correct / total:.4f} ({total} nodes)")


if __name__ == "__main__":
    main()

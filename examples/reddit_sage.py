"""Single-chip GraphSAGE training — the reference examples/pyg/reddit_quiver.py
ported to the quiver_tpu API (same loop structure: sampler.sample -> feature
gather -> model step; reference lines 116-126).

With --dataset pointing at an .npz containing {edge_index [2,E], features
[N,D], labels [N], train_idx} it trains that graph; without it, a synthetic
power-law community graph stands in (this image has no dataset egress).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np


def synthetic_reddit(n=50_000, dim=64, ncls=16, avg_deg=25, seed=0):
    """Power-law community graph; returns train AND test splits so the run
    reports an accuracy the way the reference examples do (products ~0.787,
    dist_sampling_ogb_products_quiver.py:1)."""
    rng = np.random.default_rng(seed)
    comm = rng.integers(0, ncls, n)
    # power-law-ish degrees: hubs inside each community
    deg = np.minimum((rng.pareto(1.5, n) + 1).astype(np.int64) * 3, 500)
    deg = np.maximum(deg * avg_deg // max(int(deg.mean()), 1), 2)
    src = np.repeat(np.arange(n), deg)
    # 90% intra-community edges: draw a random member of src's community
    order = np.argsort(comm, kind="stable")
    start = np.searchsorted(comm[order], np.arange(ncls))
    size = np.append(start[1:], n) - start
    c = comm[src]
    intra_pick = order[start[c] + rng.integers(0, size[c])]
    dst = np.where(rng.random(src.shape[0]) < 0.9, intra_pick, rng.integers(0, n, src.shape[0]))
    feat = np.eye(ncls, dtype=np.float32)[comm][:, : min(ncls, dim)]
    if dim > ncls:
        feat = np.concatenate(
            [feat, rng.standard_normal((n, dim - ncls)).astype(np.float32) * 0.5],
            axis=1,
        )
    labels = comm.astype(np.int32)
    perm = rng.permutation(n)
    train_idx = perm[: n // 10]
    val_idx = perm[n // 10 : n // 10 + max(n // 20, 1)]
    test_idx = perm[n // 10 + max(n // 20, 1) : n // 10 + 2 * max(n // 20, 1)]
    return np.stack([src, dst]), feat, labels, train_idx, val_idx, test_idx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dataset", default=None, help=".npz with edge_index/features/labels/train_idx")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--sizes", default="25,10")
    ap.add_argument("--cache", default="1G", help="device_cache_size")
    ap.add_argument("--mode", default="TPU", choices=["TPU", "HOST", "CPU", "GPU", "UVA"])
    ap.add_argument("--nodes", type=int, default=50_000, help="synthetic graph size")
    ap.add_argument("--dim", type=int, default=64, help="synthetic feature dim")
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--bf16", action="store_true",
                    help="bfloat16 compute (MXU-native; params/logits stay f32)")
    ap.add_argument("--model", default="sage", choices=["sage", "gat", "gcn"],
                    help="gcn = DGL GraphConv-style mini-batch GCN; "
                         "gat mirrors the reference's reddit GAT example "
                         "(dist_sampling_reddit_gat.py)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import CSRTopo, Feature
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.pyg import GraphSageSampler
    from quiver_tpu.trace import seps, timer

    if args.dataset:
        from quiver_tpu.datasets import load_npz

        data = load_npz(args.dataset)
        edge_index, feat, labels, train_idx = (
            data["edge_index"], data["features"], data["labels"], data["train_idx"],
        )
        # export_ogb.py writes the OGB split name "valid_idx"
        val_idx = data.get("valid_idx", data.get("val_idx"))
        test_idx = data.get("test_idx")
    else:
        edge_index, feat, labels, train_idx, val_idx, test_idx = synthetic_reddit(
            n=args.nodes, dim=args.dim
        )
    sizes = [int(s) for s in args.sizes.split(",")]
    ncls = int(labels.max()) + 1

    csr_topo = CSRTopo(edge_index=edge_index)
    sampler = GraphSageSampler(csr_topo, sizes=sizes, device=0, mode=args.mode)
    feature = Feature(
        rank=0, device_list=[0], device_cache_size=args.cache, csr_topo=csr_topo
    )
    feature.from_cpu_tensor(feat)

    if args.model == "gat":
        from quiver_tpu.models import GAT

        model = GAT(
            hidden_dim=args.hidden, out_dim=ncls, heads=4,
            num_layers=len(sizes), dropout=0.5,
            dtype=jnp.bfloat16 if args.bf16 else None,
        )
    elif args.model == "gcn":
        from quiver_tpu.models import GCN

        model = GCN(
            hidden_dim=args.hidden, out_dim=ncls, num_layers=len(sizes),
            dropout=0.5, dtype=jnp.bfloat16 if args.bf16 else None,
        )
    else:
        model = GraphSAGE(
            hidden_dim=args.hidden, out_dim=ncls, num_layers=len(sizes), dropout=0.5,
            dtype=jnp.bfloat16 if args.bf16 else None,
        )
    tx = optax.adam(args.lr)
    params = opt_state = None

    @jax.jit
    def train_step(params, opt_state, key, x, adjs, y):
        def loss_fn(p):
            logits = model.apply(p, x, adjs, train=True, rngs={"dropout": key})
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    labels_np = np.asarray(labels)

    def lookup(ds):
        # tier dispatch: jitted HBM path when fully resident, eager tiered
        # gather otherwise — single definition for train and eval
        if feature.shard_tensor.cpu_tensor is None:
            return feature.lookup_padded(ds.n_id)
        return feature[np.asarray(ds.n_id)]

    rng = np.random.default_rng(0)
    # small synthetic graphs can have fewer train nodes than the batch size;
    # shrink the batch so every epoch runs at least one step
    args.batch_size = min(args.batch_size, len(train_idx))
    loss = None
    for epoch in range(args.epochs):
        perm = rng.permutation(train_idx)
        t0 = time.time()
        total_edges = 0
        n_batches = 0
        for lo in range(0, len(perm) - args.batch_size + 1, args.batch_size):
            seeds = perm[lo : lo + args.batch_size]
            ds = sampler.sample_dense(seeds)
            x = lookup(ds)
            y = jnp.asarray(labels_np[np.asarray(ds.n_id)[: args.batch_size]])
            if params is None:
                params = model.init(
                    {"params": jax.random.key(0), "dropout": jax.random.key(1)}, x, ds.adjs, train=True
                )
                opt_state = tx.init(params)
            params, opt_state, loss = train_step(
                params, opt_state, jax.random.key(epoch * 10000 + lo), x, ds.adjs, y
            )
            total_edges += int(sum(int(np.asarray(a.mask).sum()) for a in ds.adjs))
            n_batches += 1
        jax.block_until_ready(loss)
        dt = time.time() - t0
        print(
            f"epoch {epoch}: {dt:.2f}s  loss={float(loss):.4f}  "
            f"SEPS={seps(total_edges, dt)/1e6:.2f}M  batches={n_batches}"
        )

    # held-out accuracy, mirroring the reference examples' final eval
    def sampled_acc(idx):
        correct = total = 0
        for lo in range(0, len(idx), args.batch_size):
            seeds = np.asarray(idx[lo : lo + args.batch_size])
            n_real = seeds.shape[0]
            if n_real < args.batch_size:  # pad to keep one compiled shape
                seeds = np.concatenate(
                    [seeds, np.full(args.batch_size - n_real, seeds[-1], seeds.dtype)]
                )
            ds = sampler.sample_dense(seeds)
            x = lookup(ds)
            logits = model.apply(params, x, ds.adjs, train=False)
            pred = np.asarray(jnp.argmax(logits, axis=-1))[:n_real]
            correct += int((pred == labels_np[seeds[:n_real]]).sum())
            total += n_real
        return correct / total, total

    if params is not None:
        for name, idx in (("val", val_idx), ("test", test_idx)):
            if idx is not None and len(idx):
                acc, total = sampled_acc(idx)
                print(f"{name} acc: {acc:.4f} ({total} nodes)")
        if args.model == "sage" and test_idx is not None and len(test_idx):
            # exact layer-wise full-neighbor inference (reference
            # SAGE.inference, dist_sampling_ogb_products_quiver.py:118-139)
            from quiver_tpu.inference import full_inference_accuracy

            facc = full_inference_accuracy(
                model, params, csr_topo, feat, labels_np, test_idx
            )
            print(f"test acc (full inference): {facc:.4f}")


if __name__ == "__main__":
    main()

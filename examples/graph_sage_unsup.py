"""Unsupervised GraphSAGE — the reference's
examples/pyg/graph_sage_unsup_quiver.py workflow re-designed for TPU:

- positive example per seed = one sampled neighbor (the reference's
  1-step `random_walk`; here one `sample_layer` draw, k=1);
- negative example = a uniform random node;
- the [seed, pos, neg] triple batch goes through the SAME sampler +
  Feature pipeline as supervised training, the model embeds all three,
  and the loss is logsigmoid on pair dot products (reference lines
  98-117);
- eval trains a linear probe on FROZEN full-graph embeddings
  (`sage_full_inference`) — the reference fits sklearn
  LogisticRegression; here the probe is a jitted softmax regression so
  the whole example stays in JAX.

Runs hermetically on CPU: JAX_PLATFORMS=cpu python examples/graph_sage_unsup.py
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=3000)
    ap.add_argument("--communities", type=int, default=4)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--sizes", default="10,10")
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--feature-signal", type=float, default=0.5)
    ap.add_argument("--dataset", default=None, help=".npz from scripts/export_ogb.py")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    import quiver_tpu as quiver
    from quiver_tpu.datasets import load_npz, synthetic_community
    from quiver_tpu.inference import sage_full_inference
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.pyg import GraphSageSampler

    if args.dataset:
        d = load_npz(args.dataset)
        edge_index, feat, labels = d["edge_index"], d["features"], d["labels"]
        train_idx = d["train_idx"]
    else:
        # community graph with a WEAK feature signal (0.5σ class nudge):
        # like the reference's Cora run, both structure and features carry
        # label information and the unsupervised loss must exploit them —
        # pass --feature-signal 0 to test pure-structure learning
        edge_index, feat, labels, train_idx = synthetic_community(
            args.nodes, communities=args.communities, dim=args.dim,
            feature_signal=args.feature_signal, seed=0,
        )
    n, dim = feat.shape  # actual dim: --dataset may differ from --dim
    topo = quiver.CSRTopo(edge_index=edge_index)
    sizes = tuple(int(s) for s in args.sizes.split(","))
    sampler = GraphSageSampler(topo, sizes=sizes, mode="TPU", seed=1)
    feature = quiver.Feature(
        rank=0, device_list=[0], device_cache_size=n * dim * 4,
        cache_policy="device_replicate", csr_topo=topo,
    )
    feature.from_cpu_tensor(feat)

    # all layers keep hidden_dim: the output IS the embedding (reference
    # SAGE class, graph_sage_unsup_quiver.py:60-76)
    model = GraphSAGE(
        hidden_dim=args.hidden, out_dim=args.hidden,
        num_layers=len(sizes), dropout=0.0,
    )
    tx = optax.adam(args.lr)

    b = min(args.batch_size, len(train_idx))

    @jax.jit
    def unsup_step(params, opt_state, x, adjs):
        def loss_fn(p):
            out = model.apply(p, x, adjs)
            z, zp, zn = out[:b], out[b : 2 * b], out[2 * b : 3 * b]
            pos = jax.nn.log_sigmoid((z * zp).sum(-1)).mean()
            neg = jax.nn.log_sigmoid(-(z * zn).sum(-1)).mean()
            return -pos - neg

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def pos_neg_batch(rng, seeds):
        """[seeds, positives, negatives]: 1-step walk + uniform negatives
        (reference sample(), graph_sage_unsup_quiver.py:44-58)."""
        nbrs, counts = sampler.sample_layer(seeds, 1)
        pos = seeds.copy()
        pos[counts > 0] = nbrs  # isolated nodes: self as positive
        neg = rng.integers(0, n, seeds.shape[0])
        return np.concatenate([seeds, pos, neg])

    def lookup(ds):
        # tier dispatch like reddit_sage: jitted HBM path when fully
        # resident, eager tiered gather otherwise
        if feature.shard_tensor.cpu_tensor is None:
            return feature.lookup_padded(ds.n_id)
        return feature[np.asarray(ds.n_id)]

    rng = np.random.default_rng(0)
    params = opt_state = None
    for epoch in range(args.epochs):
        perm = rng.permutation(train_idx)
        t0, total, nb = time.time(), 0.0, 0
        for s in range(0, len(perm) - b + 1, b):
            triple = pos_neg_batch(rng, perm[s : s + b])
            ds = sampler.sample_dense(triple)
            x = lookup(ds)
            if params is None:
                params = model.init(jax.random.key(0), x, ds.adjs)
                opt_state = tx.init(params)
            params, opt_state, loss = unsup_step(params, opt_state, x, ds.adjs)
            total += float(loss)
            nb += 1
        print(f"epoch {epoch}: loss {total / max(nb, 1):.4f} ({time.time()-t0:.1f}s)")
    if params is None:
        raise SystemExit(
            f"no training steps ran: batch size {b} exceeds the "
            f"{len(train_idx)}-node train split — lower --batch-size"
        )

    # ---- eval: linear probe on frozen full-graph embeddings ----
    emb = np.asarray(
        sage_full_inference(
            model, params,
            jnp.asarray(topo.indptr.astype(np.int32)),
            jnp.asarray(topo.indices.astype(np.int32)),
            jnp.asarray(feat),
        )
    )
    emb = (emb - emb.mean(0)) / (emb.std(0) + 1e-6)
    ncls = int(labels.max()) + 1
    rest = np.setdiff1d(np.arange(n), train_idx)
    w = jnp.zeros((emb.shape[1], ncls))
    bias = jnp.zeros((ncls,))
    probe_tx = optax.adam(0.1)
    pstate = probe_tx.init((w, bias))
    xe, ye = jnp.asarray(emb[train_idx]), jnp.asarray(labels[train_idx])

    @jax.jit
    def probe_step(wb, pstate):
        def lf(wb):
            logits = xe @ wb[0] + wb[1]
            return optax.softmax_cross_entropy_with_integer_labels(logits, ye).mean()

        loss, g = jax.value_and_grad(lf)(wb)
        up, pstate = probe_tx.update(g, pstate)
        return optax.apply_updates(wb, up), pstate, loss

    wb = (w, bias)
    for _ in range(200):
        wb, pstate, _ = probe_step(wb, pstate)
    pred = np.asarray(jnp.argmax(jnp.asarray(emb) @ wb[0] + wb[1], axis=1))
    acc_train = float((pred[train_idx] == labels[train_idx]).mean())
    acc_test = float((pred[rest] == labels[rest]).mean()) if len(rest) else acc_train
    print(f"probe acc: train {acc_train:.4f}  test {acc_test:.4f} "
          f"(chance {1 / ncls:.2f})")


if __name__ == "__main__":
    main()

"""Multi-chip GraphSAGE training over a (dp, ici) mesh — the reference's
examples/multi_gpu/pyg/ogb-products/dist_sampling_ogb_products_quiver.py
(mp.spawn + DDP + IPC hand-off, lines 139-163) re-designed as ONE process,
ONE jitted step over the device mesh: per-dp-group seed shards, hot feature
rows striped over ici, gradient psum.

Runs on any device count: a real TPU slice, or a virtual CPU mesh via
``QUIVER_VIRTUAL_DEVICES=8 python examples/products_multichip.py`` (the env
knob forces the mesh even when an accelerator plugin pre-registered).
``--pipeline fused`` selects the no-dedup structural pipeline with per-hop
ICI gathers (fastest); ``--pipeline dedup`` keeps reference-parity reindex.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np


def _maybe_force_virtual_devices():
    """QUIVER_VIRTUAL_DEVICES=N forces an N-device CPU mesh even when an
    accelerator plugin pre-registered (env vars alone lose to it)."""
    n = os.environ.get("QUIVER_VIRTUAL_DEVICES")
    if not n:
        return
    from quiver_tpu.utils import force_virtual_cpu_devices

    force_virtual_cpu_devices(int(n))


def main():
    _maybe_force_virtual_devices()
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-per-dp", type=int, default=256)
    ap.add_argument("--nodes", type=int, default=100_000)
    ap.add_argument("--avg-deg", type=int, default=15)
    ap.add_argument("--dim", type=int, default=100)
    ap.add_argument("--classes", type=int, default=47)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--sizes", default="15,10,5")
    ap.add_argument("--steps-per-epoch", type=int, default=0, help="0 = full epoch")
    ap.add_argument("--pipeline", default="dedup", choices=["dedup", "fused"])
    ap.add_argument("--hosts", type=int, default=0,
                    help="add a DCN host axis: (host, dp, ici) mesh")
    ap.add_argument("--topology", default="replicated",
                    choices=["replicated", "sharded"],
                    help="sharded = row-shard the CSR over the mesh (no chip "
                         "holds the full graph; the papers100M layout)")
    ap.add_argument("--bf16", action="store_true",
                    help="bfloat16 compute (MXU-native; params/logits stay f32)")
    ap.add_argument("--hot-frac", type=float, default=0.0,
                    help="replicate this heat-ordered fraction of the feature "
                         "table per host; only the cold remainder rides DCN "
                         "(needs --hosts >= 2)")
    ap.add_argument("--label-signal", type=float, default=1.5,
                    help="class-signal strength of the synthetic features; "
                         "lower = harder task (accuracy anchors use a value "
                         "that keeps the anchor off the 1.0 ceiling)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from quiver_tpu import CSRTopo
    from quiver_tpu.datasets import synthetic_powerlaw
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.parallel import (
        make_mesh,
        make_sharded_topo_train_step,
        make_sharded_train_step,
        replicate,
        shard_feature_hot_cold,
        shard_feature_rows,
        shard_topology_rows,
    )
    from quiver_tpu.pyg.sage_sampler import sample_dense_pure

    rng = np.random.default_rng(0)
    n = args.nodes
    e = n * args.avg_deg
    # learnable power-law graph (class-dependent feature nudge) so the run
    # reports a meaningful accuracy like the reference products example
    edge_index, feat, labels, train_idx = synthetic_powerlaw(
        n, e, dim=args.dim, classes=args.classes, train_frac=0.3, seed=0,
        label_signal=args.label_signal,
    )
    rest = np.setdiff1d(np.arange(n), train_idx)
    val_idx, test_idx = rest[: n // 20], rest[n // 20 : n // 10]
    if args.hot_frac:
        # heat-order the id space so the hot prefix is the replicated tier
        from quiver_tpu.utils import heat_reorder

        edge_index, feat, labels, (train_idx, val_idx, test_idx), _, _ = (
            heat_reorder(
                edge_index, n, feat, labels, (train_idx, val_idx, test_idx)
            )
        )
    topo = CSRTopo(edge_index=edge_index)

    mesh = make_mesh(hosts=args.hosts or None)
    from quiver_tpu.parallel import mesh_axes

    data_axes, _, dp = mesh_axes(mesh)
    data_spec = P(data_axes)
    print(
        f"mesh: {dict(mesh.shape)} ({mesh.devices.size} devices), "
        f"{dp} data-parallel groups"
    )

    sizes = tuple(int(s) for s in args.sizes.split(","))
    model = GraphSAGE(
        hidden_dim=args.hidden, out_dim=args.classes, num_layers=len(sizes),
        dropout=0.5, dtype=jnp.bfloat16 if args.bf16 else None,
    )
    tx = optax.adam(1e-3)
    hot_rows = int(n * args.hot_frac) if args.hot_frac else None
    cold_budget = None
    if hot_rows:
        # probe-calibrated cold-lane fraction (margin like the sampler caps)
        from quiver_tpu.parallel import calibrate_cold_budget
        from quiver_tpu.pyg import GraphSageSampler

        probe_sampler = GraphSageSampler(topo, sizes=sizes, mode="TPU", seed=7)
        probes = [rng.choice(train_idx, min(64, len(train_idx))) for _ in range(4)]
        cold_budget = calibrate_cold_budget(probe_sampler, probes, hot_rows)
        print(f"hot tier: {hot_rows} rows, calibrated cold budget {cold_budget:.2f}")
    if args.topology == "sharded":
        step = make_sharded_topo_train_step(
            mesh, model, tx, sizes=sizes, pipeline=args.pipeline,
            hot_rows=hot_rows, cold_budget=cold_budget,
        )
        stopo = shard_topology_rows(mesh, topo)
    else:
        step = make_sharded_train_step(
            mesh, model, tx, sizes=sizes, pipeline=args.pipeline,
            hot_rows=hot_rows, cold_budget=cold_budget,
        )
        indptr = replicate(mesh, topo.indptr.astype(np.int32))
        indices = replicate(mesh, topo.indices.astype(np.int32))
    if hot_rows:
        feat_sharded = shard_feature_hot_cold(mesh, feat, hot_rows)
    else:
        feat_sharded = shard_feature_rows(mesh, feat)
    labels_d = replicate(mesh, labels)

    batch_global = args.batch_per_dp * dp
    ds0 = sample_dense_pure(
        jnp.asarray(topo.indptr.astype(np.int32)),
        jnp.asarray(topo.indices.astype(np.int32)),
        jax.random.key(0),
        jnp.arange(args.batch_per_dp, dtype=jnp.int32),
        sizes,
    )
    x0 = jnp.zeros((ds0.n_id.shape[0], args.dim), jnp.float32)
    params = replicate(
        mesh,
        model.init({"params": jax.random.key(1), "dropout": jax.random.key(2)}, x0, ds0.adjs, train=True),
    )
    opt_state = jax.device_put(tx.init(params), NamedSharding(mesh, P()))

    steps_per_epoch = args.steps_per_epoch or max(len(train_idx) // batch_global, 1)
    for epoch in range(args.epochs):
        t0 = time.time()
        for i in range(steps_per_epoch):
            seeds = jax.device_put(
                jnp.asarray(rng.choice(train_idx, batch_global).astype(np.int32)),
                NamedSharding(mesh, data_spec),
            )
            step_key = jax.random.key(epoch * 100000 + i)
            if args.topology == "sharded":
                out = step(params, opt_state, step_key, stopo, feat_sharded,
                           labels_d, seeds)
            else:
                out = step(params, opt_state, step_key, indptr, indices,
                           feat_sharded, labels_d, seeds)
            if hot_rows:
                params, opt_state, loss, overflow = out
            else:
                (params, opt_state, loss), overflow = out, None
        jax.block_until_ready(loss)
        dt = time.time() - t0
        ov = f"  cold_overflow={int(overflow)}" if overflow is not None else ""
        print(
            f"epoch {epoch}: {dt:.2f}s  loss={float(loss):.4f}  "
            f"{steps_per_epoch * batch_global / dt:.0f} seeds/s{ov}"
        )

    # val/test accuracy (reference products example reports ~0.787 on the
    # real dataset; this synthetic stand-in records the framework's number
    # for round-over-round regression visibility)
    from quiver_tpu.inference import sampled_eval
    from quiver_tpu.pyg import GraphSageSampler

    host_params = jax.tree_util.tree_map(np.asarray, params)
    eval_sampler = GraphSageSampler(topo, sizes=sizes, mode="TPU", seed=123)
    for name, idx in (("val", val_idx), ("test", test_idx)):
        if len(idx):
            acc = sampled_eval(
                model, host_params, eval_sampler, feat, labels, idx,
                batch_size=min(1024, len(idx)),
            )
            print(f"{name} acc: {acc:.4f} ({len(idx)} nodes)")


if __name__ == "__main__":
    main()

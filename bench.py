"""Headline benchmark: k-hop neighbor sampling throughput (SEPS), plus
feature-collection GB/s and an end-to-end epoch-equivalent train loop.

Mirrors the reference's benchmarks/sample/bench_sampler.py (SEPS = sampled
edges per second, bench_sampler.py:14-16) on an ogbn-products-scale synthetic
graph, fanout [15, 10, 5], batch 1024 — the config behind the reference's
headline 34.29M SEPS UVA number (docs/Introduction_en.md:41, BASELINE.md).
Context also records:

- feature gather GB/s (reference benchmarks/feature/bench_feature.py:44-46;
  baseline 14.82 GB/s 20%-cache 1-GPU, docs/Introduction_en.md:95) on the
  jitted HBM path and the tiered (hot HBM + host cold) prefetch path;
- e2e epoch-equivalent seconds for the FULL train step (sample -> feature
  gather -> fwd/bwd -> adam, all one XLA program), fused and dedup sampling,
  vs the reference's 11.1 s 1-GPU products epoch
  (docs/Introduction_en.md:144-149) — this charges the fused path's
  duplicated-n_id gather volume end to end.

Measurement discipline: the TPU here sits behind the axon tunnel, where every
dispatch costs ~0.1-1 s of RPC latency — a host-side timing loop measures the
network, not the chip. Every device benchmark therefore (a) runs its iteration
loop INSIDE jit (`lax.scan`), so one dispatch covers all iterations and one
dependent scalar fetch ends the clock, (b) sizes the window so device compute
is seconds, not milliseconds (round 3 under-reported every rate up to 5x by
timing ~0.15 s windows against a ~0.11 s dispatch floor — PERF_NOTES.md), and
(c) subtracts the measured per-dispatch floor (`rpc_floor_s` in context) from
op-rate denominators. The e2e section needs no correction: it times one FULL
epoch (193 steps) as one dispatch, which is exactly what a user pays. A
wall-clock budget (default 480 s, env QUIVER_BENCH_BUDGET_S) skips later
sections rather than losing the JSON.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline", "context"}.
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_SEPS = 34.29e6  # reference: 1 GPU, UVA, ogbn-products [15,10,5]
BASELINE_FEAT_GBPS = 14.82  # reference: 1 GPU, 20% cache, products (Introduction_en.md:95)
BASELINE_EPOCH_S = 11.1  # reference: 1 GPU products GraphSAGE epoch (Introduction_en.md:144)
PRODUCTS_TRAIN_NODES = 196_615  # ogbn-products train split size

_T0 = time.time()
_BUDGET_S = float(os.environ.get("QUIVER_BENCH_BUDGET_S", "480"))


def remaining() -> float:
    return _BUDGET_S - (time.time() - _T0)


_RPC_FLOOR_S = 0.0


def measure_rpc_floor():
    """Fixed cost of one dispatch+fetch through the tunnel (~0.11 s here,
    ~0 on a real TPU VM). Subtracted from op-rate windows; min of 4 reps is
    the deterministic part (the jitter above it stays in the measurement,
    which is the conservative direction)."""
    global _RPC_FLOOR_S
    import jax
    import jax.numpy as jnp

    triv = jax.jit(lambda x: x + 1.0)
    float(triv(jnp.float32(0)))  # compile
    reps = []
    for i in range(4):
        t0 = time.time()
        float(triv(jnp.float32(i)))
        reps.append(time.time() - t0)
    _RPC_FLOOR_S = min(reps)
    log(f"rpc dispatch floor: {_RPC_FLOOR_S*1e3:.0f} ms")
    return _RPC_FLOOR_S


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def enable_compile_cache():
    """Persistent XLA compile cache next to the repo: repeat runs of the
    same shapes skip the (remote) compile entirely."""
    import jax

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as exc:  # cache is an optimization, never a requirement
        log(f"compile cache unavailable: {exc}")


def build_graph(n_nodes=2_449_029, n_edges=2 * 61_859_140, seed=0):
    """products-scale power-law graph. Node count = ogbn-products; edge
    count = 2x the published 61.86M because products is UNDIRECTED and the
    reference samples the symmetrized CSR (avg degree ~50). The power-law
    degree profile matches the published skew (docs/Introduction_en.md:77-80)
    — a uniform random graph would misrepresent both the dedup pipeline's
    subgraph sizes and cache-hit behaviour. Cached on disk next to the
    compile cache: generation costs ~90 s, reloading ~3 s."""
    cache = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f".bench_graph_{n_nodes}_{n_edges}_{seed}.npz",
    )
    if os.path.exists(cache):
        try:
            log(f"loading cached graph: {cache}")
            data = np.load(cache)
            return data["indptr"], data["indices"]
        except Exception as exc:  # truncated/corrupt cache: regenerate
            log(f"graph cache unreadable ({exc}); regenerating")
            try:
                os.remove(cache)
            except OSError:
                pass
    from quiver_tpu.datasets import powerlaw_csr

    log(f"generating power-law graph: {n_nodes} nodes, {n_edges} edges")
    indptr, indices = powerlaw_csr(n_nodes, n_edges, seed=seed)
    try:  # atomic write (tmp + rename): a killed run must not leave a
        # truncated cache that poisons every later run. Uncompressed ~0.5 GB.
        tmp = cache + ".tmp"
        with open(tmp, "wb") as fh:
            np.savez(fh, indptr=indptr, indices=indices.astype(np.int32))
        os.replace(tmp, cache)
    except OSError as exc:
        log(f"graph cache not written: {exc}")
    return indptr, indices


def make_scanned_sampler(sample_fn, sizes, iters, caps=None):
    """One jitted program running `iters` sample iterations in a lax.scan —
    a single dispatch + a single dependent fetch, so tunnel RPC latency is
    amortized across the whole run instead of multiplying it.

    EVERY sample output is consumed (n_id, cols, masks): a mask-only edge
    count lets XLA dead-code-eliminate the neighbor-id gathers entirely
    (masks depend only on degrees — measured 8 vs 29 ms/iter,
    scripts/probe_seps_dce.py), which would bench a program that never
    materializes the sample the reference's SEPS metric counts (round-3/
    early-round-4 numbers had this flaw; PERF_NOTES.md "SEPS correction").

    The graph rides the TILED layout (bd, tiles — the library's TPU-mode
    default); `caps` (dedup leg) are the calibrated static caps, with the
    summed cap_overflow returned as output [2] so the harness can assert
    the capped run dropped NOTHING (same edges as uncapped = exact
    reference semantics, just less padding).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from quiver_tpu.ops.sample import tiled_sample_layer

    @jax.jit
    def run_many(bd, tiles, key0, seeds_all):
        m = seeds_all.shape[0]

        def hop(cur, cur_valid, k, key):
            return tiled_sample_layer(bd, tiles, cur, cur_valid, k, key)

        def body(carry, i):
            acc, tacc, oacc = carry
            key = jax.random.fold_in(key0, i)
            if caps is None:
                ds = sample_fn(None, None, key, seeds_all[i % m], sizes, sample_fn=hop)
            else:
                ds = sample_fn(
                    None, None, key, seeds_all[i % m], sizes, caps, sample_fn=hop
                )
            edges = sum(adj.mask.sum(dtype=jnp.int32) for adj in ds.adjs)
            # checksum over every other output, returned as a PROGRAM
            # OUTPUT — an accumulator that algebraically cancels (x+0) or
            # is never fetched would be optimized away again
            touch = ds.n_id.sum(dtype=jnp.int32) + ds.count
            for adj in ds.adjs:
                if adj.cols is not None:
                    touch = touch + adj.cols.sum(dtype=jnp.int32)
            ov = jnp.int32(0) if ds.cap_overflow is None else ds.cap_overflow
            return (acc + edges, tacc + touch, oacc + ov), None

        (acc, touch, oacc), _ = lax.scan(
            body,
            (jnp.int32(0), jnp.int32(0), jnp.int32(0)),
            jnp.arange(iters, dtype=jnp.int32),
        )
        # ONE fetchable output (a second int() would be a second ~0.11 s
        # D2H round trip inside the timed window)
        return jnp.stack([acc, touch, oacc])

    return run_many


def bench_sampling(context, bd, tiles, seeds_all, caps, iters=200):
    import jax

    from quiver_tpu.pyg.sage_sampler import sample_dense_fused, sample_dense_pure

    sizes = (15, 10, 5)
    results = {}
    for name, fn, leg_caps in (
        ("fused", sample_dense_fused, None),
        ("dedup", sample_dense_pure, caps),
    ):
        if remaining() < 60:
            log(f"budget exhausted before {name} sampling bench")
            break
        try:
            run = make_scanned_sampler(fn, sizes, iters, caps=leg_caps)
            log(f"compiling {name} pipeline...")
            t0 = time.time()
            out = np.asarray(run(bd, tiles, jax.random.key(0), seeds_all))
            compile_s = time.time() - t0
            t0 = time.time()
            out = np.asarray(run(bd, tiles, jax.random.key(1), seeds_all))
            dt = max(time.time() - t0 - _RPC_FLOOR_S, 1e-9)
            total, overflow = int(out[0]), int(out[2])
            seps = total / dt
            log(
                f"{name:5s}: {seps/1e6:.2f}M SEPS ({total} edges, {iters} iters in "
                f"{dt:.2f}s net of floor; compile+first {compile_s:.1f}s"
                + (f", cap_overflow {overflow}" if leg_caps is not None else "")
                + ")"
            )
            results[name] = seps
            context[f"{name}_compile_s"] = round(compile_s, 1)
            context[f"{name}_seps"] = round(seps, 1)
            context[f"{name}_vs_uva_baseline"] = round(seps / BASELINE_SEPS, 4)
            if leg_caps is not None:
                context["dedup_sampling_cap_overflow"] = overflow
        except Exception as exc:  # one leg failing must not lose the JSON
            log(f"{name} sampling bench failed: {exc}")
    return results


def bench_feature(context, table_dev, iters=800, batch=262_144):
    """Feature-collection GB/s, products-like table (N x 100 f32 = 0.98 GB).

    hot: fully HBM-resident jitted gather (the honest TPU-native design —
    the whole products table fits one chip's HBM, so the reference's 20%
    cache split is unnecessary at this scale); iterations scanned in-jit.
    tiered: 20% HBM hot prefix + host cold tier through the REAL prefetch
    pipeline (`TieredFeaturePipeline.prepare` + `tiered_lookup`) with the
    reference's power-law skew (80% of reads in the hot 20%,
    docs/Introduction_en.md:77-80). Host work + per-batch dispatch are the
    honest cost of that path; under the axon tunnel the H2D copy and RPC
    dominate (on a TPU VM they ride PCIe).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from quiver_tpu import Feature
    from quiver_tpu.pipeline import TieredFeaturePipeline, tiered_lookup

    n_nodes, dim = table_dev.shape
    rng = np.random.default_rng(0)
    log(f"feature table: {n_nodes} x {dim} f32")

    hot_n = n_nodes // 5
    hot_ids = rng.integers(0, hot_n, int(batch * 0.8))
    cold_ids = rng.integers(hot_n, n_nodes, batch - hot_ids.shape[0])
    ids = np.concatenate([hot_ids, cold_ids])
    rng.shuffle(ids)

    # --- hot: all rows in HBM, iters gathers scanned inside one program
    ids_dev = jax.device_put(jnp.asarray(ids.astype(np.int32)))

    @jax.jit
    def gather_many(tab, idx):
        def body(acc, i):
            shifted = (idx + i * 977) % tab.shape[0]  # decorrelate iterations
            return acc + jnp.take(tab, shifted, axis=0).sum(dtype=jnp.float32), None

        acc, _ = lax.scan(body, jnp.float32(0), jnp.arange(iters, dtype=jnp.int32))
        return acc

    float(gather_many(table_dev, ids_dev))  # compile + warm
    t0 = time.time()
    float(gather_many(table_dev, ids_dev))
    dt = max(time.time() - t0 - _RPC_FLOOR_S, 1e-9)
    hot_gbps = iters * batch * dim * 4 / dt / 1e9
    log(f"feature hot HBM: {hot_gbps:.2f} GB/s ({iters} gathers in {dt:.3f}s net)")
    context["feature_hot_gbps"] = round(hot_gbps, 2)
    context["feature_hot_mrows_per_s"] = round(iters * batch / dt / 1e6, 1)
    context["feature_hot_vs_ref_20pct"] = round(hot_gbps / BASELINE_FEAT_GBPS, 2)
    # TPU row gathers are DMA-descriptor-rate bound at ~90-95M rows/s for
    # dim<=128 (PERF_NOTES.md; round 3's "20M rows/s wall" was the RPC
    # dispatch floor polluting a 0.15 s window)

    # --- tiered 20% through the real prefetch pipeline. Host-side table is
    # generated fresh (pulling the device table back over the tunnel costs
    # minutes); only the hot 20% is uploaded. Content differs from the hot
    # bench's device table — irrelevant, throughput only. Iteration count is
    # small and fixed: each iteration pays real host-gather + tunnel H2D.
    iters = 4
    table_host = rng.standard_normal((n_nodes, dim)).astype(np.float32)
    feat = Feature(rank=0, device_list=[0], device_cache_size=hot_n * dim * 4)
    feat.from_cpu_tensor(table_host)
    pipe = TieredFeaturePipeline(feat)

    def merge_sum(hot, mapped, cold_rows, cold_pos):
        return tiered_lookup(hot, mapped, cold_rows, cold_pos).sum(dtype=jnp.float32)

    m = jax.jit(merge_sum)
    ids_j = jnp.asarray(ids)
    float(m(pipe.hot_table, *pipe.prepare(ids_j)))  # compile + warm
    t0 = time.time()
    acc = jnp.float32(0)
    for _ in range(iters):
        acc = acc + m(pipe.hot_table, *pipe.prepare(ids_j))
    float(acc)
    dt = time.time() - t0
    tiered_gbps = iters * batch * dim * 4 / dt / 1e9
    log(f"feature tiered 20% (prefetch pipeline): {tiered_gbps:.2f} GB/s")
    context["feature_tiered20_gbps"] = round(tiered_gbps, 2)


def bench_quant_feature(context, table_dev, iters=800, batch=262_144):
    """Quantized feature store (quiver_tpu.quant): fused dequant-on-gather
    GB/s for the int8 codec on the hot HBM path, next to the fp32 hot rate
    from `bench_feature`. The table is ENCODED ON DEVICE (one jitted pass;
    shipping a host-encoded copy through the tunnel would cost minutes) and
    the gather+decode loop scans in-jit like every other device bench.
    Reported both ways: wire-true GB/s via `trace.gbps(bytes_per_elem=1)`
    (the bytes the gather actually touches) and the f32-equivalent rate
    (rows delivered x 4 B — comparable to the fp32 row). Row-rate-bound
    regimes (PERF_NOTES.md) should show similar ROW rates with 1/4 the
    bytes touched; the f32-equivalent number is then roughly the fp32 rate
    while HBM pressure drops 4x."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from quiver_tpu.quant import get_codec
    from quiver_tpu.trace import gbps

    codec = get_codec("int8")
    n_nodes, dim = table_dev.shape
    rng = np.random.default_rng(3)
    ids_dev = jax.device_put(
        jnp.asarray(rng.integers(0, n_nodes, batch).astype(np.int32))
    )

    @jax.jit
    def encode_dev(tab):
        # device-side mirror of Int8Codec.encode: bit-identical payload
        # (np.rint == jnp.round half-to-even, span-0 rows store q=0);
        # scale/zero may differ by 1 ulp (XLA lowers the /254 constant
        # divide to a reciprocal multiply) — irrelevant for a throughput
        # bench. Host-exact encode lives in quant.codecs; this exists only
        # because shipping a host-encoded table through the tunnel costs
        # minutes.
        rmin = tab.min(axis=1)
        span = tab.max(axis=1) - rmin
        pos = span > 0
        scale = jnp.where(pos, span / 254.0, 1.0)
        inv = jnp.where(pos, 254.0 / jnp.where(pos, span, 1.0), 0.0)
        q = jnp.clip(
            jnp.round((tab - rmin[:, None]) * inv[:, None]) - 127.0, -127, 127
        ).astype(jnp.int8)
        q = q * pos[:, None].astype(q.dtype)  # span-0 rows store q=0
        zero = jnp.where(pos, -127.0 - rmin / scale, -rmin)
        return q, scale, zero

    q, scale, zero = encode_dev(table_dev)
    q.block_until_ready()

    @jax.jit
    def gather_dequant_many(payload, s, z, idx):
        def body(acc, i):
            shifted = (idx + i * 977) % payload.shape[0]
            rows = jnp.take(payload, shifted, axis=0).astype(jnp.float32)
            rows = (rows - jnp.take(z, shifted)[:, None]) * jnp.take(s, shifted)[:, None]
            return acc + rows.sum(dtype=jnp.float32), None

        acc, _ = lax.scan(body, jnp.float32(0), jnp.arange(iters, dtype=jnp.int32))
        return acc

    float(gather_dequant_many(q, scale, zero, ids_dev))  # compile + warm
    t0 = time.time()
    float(gather_dequant_many(q, scale, zero, ids_dev))
    dt = max(time.time() - t0 - _RPC_FLOOR_S, 1e-9)
    wire = gbps(iters * batch, dim, dt, bytes_per_elem=codec.bytes_per_elem)
    f32eq = gbps(iters * batch, dim, dt)
    log(
        f"quant int8 fused dequant-gather: {wire:.2f} GB/s wire "
        f"({f32eq:.2f} GB/s f32-equiv, {iters * batch / dt / 1e6:.1f}M rows/s; "
        f"hot capacity x{codec.capacity_multiplier(dim):.2f} at D={dim})"
    )
    context["quant_int8_gather_gbps_wire"] = round(wire, 2)
    context["quant_int8_gather_gbps_f32equiv"] = round(f32eq, 2)
    context["quant_int8_mrows_per_s"] = round(iters * batch / dt / 1e6, 1)
    context["quant_int8_hot_capacity_multiplier"] = round(
        codec.capacity_multiplier(dim), 2
    )


def bench_host_sampler(context, indptr_np, indices_np, seeds_np, iters=3):
    """Host-engine SEPS on the products-shaped graph — the direct
    comparison against the reference's CPU sampler baseline (1.84M SEPS,
    BASELINE.md row 1; docs/Introduction_en.md:40). Measures the FULL
    HostSampler path (native k-subset engine + host reindex), not just the
    kernel; `make -C quiver_tpu/csrc bench` has the kernel-only number."""
    from quiver_tpu.ops.cpu_kernels import HostSampler, native_available

    if not native_available():
        # the numpy fallback's per-row Python loop takes MINUTES at this
        # graph size — skipping beats starving the e2e sections' budget
        log("host sampler bench skipped: native engine not built")
        return
    hs = HostSampler(indptr_np.astype(np.int64), indices_np.astype(np.int64))
    sizes = (15, 10, 5)
    m = seeds_np.shape[0]
    # warm one batch (page-in, allocator)
    hs.sample_multilayer(seeds_np[0], sizes, seed=99)
    t0 = time.time()
    total = 0
    for i in range(iters):
        _, _, adjs = hs.sample_multilayer(seeds_np[i % m], sizes, seed=i)
        total += sum(int(a["mask"].sum()) for a in adjs)
    dt = time.time() - t0
    host_seps = total / dt
    log(
        f"host sampler: {host_seps/1e6:.2f}M SEPS (native={native_available()}, "
        f"{iters} batches in {dt:.2f}s; ref CPU baseline 1.84M)"
    )
    context["host_seps"] = round(host_seps, 1)
    context["host_seps_vs_ref_cpu"] = round(host_seps / 1.84e6, 2)


def calibrate_bench_caps(indptr, indices, seeds_all, batch, sizes=(15, 10, 5)):
    """THE cap policy for every dedup section of this bench (one definition
    so logged caps always match the caps the e2e step runs): probe over ALL
    seed batches, margin 1.1, granule 2048. The tight margin (vs the 1.2
    library default) is safe because the probe pool IS the epoch's seed pool
    — and any residual drop shows up in the reported cap_overflow counter
    (0 == exact reference semantics)."""
    from quiver_tpu.pyg.sage_sampler import caps_from_counts, probe_hop_counts

    import jax

    counts = probe_hop_counts(indptr, indices, jax.random.key(0), seeds_all, sizes)
    caps = caps_from_counts(counts, batch, sizes, margin=1.1, granule=2048)
    log(f"dedup hop unique counts max {counts.max(axis=0).tolist()} -> caps {caps}")
    return caps


def bench_e2e(context, bd, tiles, seeds_all, table, iters=None, classes=47, caps=None):
    """True e2e epoch: ONE jitted program scans a full epoch's worth of train
    steps (sample -> feature gather -> 3-layer GraphSAGE fwd/bwd -> adam),
    ceil(196615/1024) = 193 steps, timed as one dispatch + one dependent
    fetch — no extrapolation, and the single dispatch cost is included
    because a real epoch pays it too. Charges the fused path's
    duplicated-n_id gather volume against its sampling win."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax import lax

    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.ops.sample import tiled_sample_layer
    from quiver_tpu.pyg.sage_sampler import (
        sample_and_gather_dedup,
        sample_and_gather_fused,
    )

    sizes = (15, 10, 5)
    batch = seeds_all.shape[1]
    n_nodes, dim = table.shape
    steps_per_epoch = -(-PRODUCTS_TRAIN_NODES // batch)
    if iters is None:
        iters = steps_per_epoch
    labels = jax.jit(
        lambda k: jax.random.randint(k, (n_nodes,), 0, classes, jnp.int32)
    )(jax.random.key(8))
    model = GraphSAGE(hidden_dim=256, out_dim=classes, num_layers=3, dropout=0.0)
    tx = optax.adam(1e-3)

    def make_epoch(sample_fn, sample_caps):
        def one_step(params, opt_state, g_bd, g_tiles, tab, lab, key, seeds):
            key, sub = jax.random.split(key)

            def hop(cur, cur_valid, k, hkey):
                return tiled_sample_layer(g_bd, g_tiles, cur, cur_valid, k, hkey)

            if sample_fn is sample_and_gather_fused:
                # per-hop interleaved gather: XLA overlaps each hop's
                # (row-rate-bound) feature fetch with the next hop's sampling
                ds, x = sample_and_gather_fused(
                    None, None, tab, sub, seeds, sizes, sample_fn=hop
                )
            else:
                # reference-parity dedup DAG with the structural last hop:
                # leaf features ride one constant-table gather (no cols
                # gather from activations, no backward scatter)
                ds, x = sample_and_gather_dedup(
                    None, None, tab, sub, seeds, sizes, sample_caps,
                    sample_fn=hop,
                )
            y = jnp.take(lab, jnp.clip(ds.n_id[:batch], 0, lab.shape[0] - 1))

            def objective(p):
                logits = model.apply(p, x, ds.adjs, train=True, rngs={"dropout": key})
                ll = jax.nn.log_softmax(logits)
                return -jnp.take_along_axis(ll, y[:, None], axis=1).mean()

            loss, grads = jax.value_and_grad(objective)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            ov = jnp.int32(0) if ds.cap_overflow is None else ds.cap_overflow
            return params, opt_state, loss, ov

        @jax.jit
        def epoch(params, opt_state, g_bd, g_tiles, tab, lab, key0, seeds_all):
            m = seeds_all.shape[0]

            def body(carry, i):
                params, opt_state = carry
                key = jax.random.fold_in(key0, i)
                params, opt_state, loss, ov = one_step(
                    params, opt_state, g_bd, g_tiles, tab, lab, key, seeds_all[i % m]
                )
                return (params, opt_state), (loss, ov)

            (params, opt_state), (losses, ovs) = lax.scan(
                body, (params, opt_state), jnp.arange(iters, dtype=jnp.int32)
            )
            return params, opt_state, losses, ovs.sum()

        return epoch

    fused_probe = None
    for name, sample_fn, sample_caps in (
        ("fused", sample_and_gather_fused, None),
        ("dedup", sample_and_gather_dedup, caps),
    ):
        # a cold-cache compile of one e2e program runs ~70-100 s; skip the
        # leg outright rather than blow the budget mid-compile with no JSON
        if remaining() < 150:
            log(f"budget exhausted before e2e {name}")
            break
        def hop0(cur, cur_valid, k, hkey):
            return tiled_sample_layer(bd, tiles, cur, cur_valid, k, hkey)

        if sample_fn is sample_and_gather_fused:
            ds_real, x0 = sample_and_gather_fused(
                None, None, table, jax.random.key(0), jnp.asarray(seeds_all[0]),
                sizes, sample_fn=hop0,
            )
        else:
            ds_real, x0 = sample_and_gather_dedup(
                None, None, table, jax.random.key(0), jnp.asarray(seeds_all[0]),
                sizes, sample_caps, sample_fn=hop0,
            )
        params = model.init(jax.random.key(1), x0, ds_real.adjs)
        opt_state = tx.init(params)
        epoch_fn = make_epoch(sample_fn, sample_caps)
        log(f"compiling e2e {name} step...")
        t0 = time.time()
        params, opt_state, losses, ov = epoch_fn(
            params, opt_state, bd, tiles, table, labels, jax.random.key(2), seeds_all
        )
        float(losses[-1])
        compile_s = time.time() - t0
        t0 = time.time()
        params, opt_state, losses, ov = epoch_fn(
            params, opt_state, bd, tiles, table, labels, jax.random.key(3), seeds_all
        )
        float(losses[-1])  # dependent fetch == all steps executed
        dt = time.time() - t0
        step_s = max(dt - _RPC_FLOOR_S, 1e-9) / iters
        # one dispatch IS one epoch when iters == steps_per_epoch; otherwise
        # extrapolate the net step time and add the one dispatch an epoch pays
        epoch_s = dt if iters == steps_per_epoch else step_s * steps_per_epoch + _RPC_FLOOR_S
        overflow = int(ov)
        log(
            f"e2e {name}: {step_s*1e3:.1f} ms/step -> epoch {epoch_s:.2f}s "
            f"({iters} steps in one dispatch, compile {compile_s:.1f}s, "
            f"cap_overflow {overflow}, ref 1-GPU epoch {BASELINE_EPOCH_S}s)"
        )
        context[f"e2e_{name}_epoch_s"] = round(epoch_s, 2)
        context[f"e2e_{name}_step_ms"] = round(step_s * 1e3, 1)
        context[f"e2e_{name}_compile_s"] = round(compile_s, 1)
        context[f"e2e_{name}_vs_ref_epoch"] = round(BASELINE_EPOCH_S / epoch_s, 2)
        if name == "dedup":
            # unique nodes dropped by the static caps across the timed run:
            # 0 means the tight margin cost nothing semantically
            context["e2e_dedup_cap_overflow"] = overflow
        if name == "fused":
            # keep the fused leg's pieces for the compute-share probe,
            # which runs AFTER both legs (the dedup headline outranks it
            # when the budget is tight)
            fused_probe = (params, opt_state, x0, ds_real.adjs, step_s)
    if fused_probe is not None and remaining() > 90:
        params, opt_state, x0, adjs0, step_s = fused_probe
        # compute share: a model-only epoch (fwd/bwd + adam on fixed
        # sampled inputs, same scan length) against the full step.
        # x is perturbed per iteration so XLA cannot hoist the
        # params-independent aggregation means out of the scan.
        @jax.jit
        def model_epoch(params, opt_state, x, adjs, lab, seeds0, key0):
            y = jnp.take(lab, jnp.clip(seeds0, 0, lab.shape[0] - 1))

            def body(carry, i):
                p, o = carry
                key = jax.random.fold_in(key0, i)
                xx = x + (i.astype(x.dtype) * 1e-9)

                def objective(pp):
                    logits = model.apply(
                        pp, xx, adjs, train=True, rngs={"dropout": key}
                    )
                    ll = jax.nn.log_softmax(logits)
                    return -jnp.take_along_axis(ll, y[:, None], axis=1).mean()

                loss, grads = jax.value_and_grad(objective)(p)
                updates, o = tx.update(grads, o, p)
                p = optax.apply_updates(p, updates)
                return (p, o), loss

            (_, _), losses = lax.scan(
                body, (params, opt_state), jnp.arange(iters, dtype=jnp.int32)
            )
            return losses

        margs = (
            params, opt_state, x0, adjs0, labels,
            jnp.asarray(seeds_all[0]),
        )
        t0 = time.time()
        float(model_epoch(*margs, jax.random.key(9))[-1])
        mc = time.time() - t0
        t0 = time.time()
        float(model_epoch(*margs, jax.random.key(10))[-1])
        dt2 = max(time.time() - t0 - _RPC_FLOOR_S, 1e-9)
        compute_ms = dt2 * 1e3 / iters
        context["e2e_compute_ms_per_step"] = round(compute_ms, 2)
        context["e2e_compute_frac"] = round(compute_ms / (step_s * 1e3), 3)
        log(
            f"e2e compute share: model-only {compute_ms:.1f} ms of "
            f"{step_s*1e3:.1f} ms/step = {compute_ms/(step_s*1e3):.0%} "
            f"(compile {mc:.1f}s)"
        )


def bench_stream(context, n=50_000, deg=8, edges_per_commit=512, reps=5):
    """Round-17 streaming-graph delta-apply costs — the MEASURED inputs
    of `scaling.delta_table` (``stream_append_s`` per edge,
    ``stream_swap_s`` per batched device commit): one
    `stream.StreamingTiledGraph` over a synthetic graph, a fresh
    ``edges_per_commit``-edge `GraphDelta` applied per rep. The host
    half (pad-lane writes + adjacency bookkeeping) is isolated on a
    device_arrays=False twin, so the swap number is the batched
    tile/bd row-scatter alone — the part a fenced `update_graph`
    serializes against serving."""
    from quiver_tpu import CSRTopo
    from quiver_tpu.stream import GraphDelta, StreamingTiledGraph

    rng = np.random.default_rng(23)
    src = np.repeat(np.arange(n, dtype=np.int64), deg)
    dst = rng.integers(0, n, src.shape[0])
    topo = CSRTopo(edge_index=np.stack([src, dst]))

    import jax

    def deltas(seed):
        r = np.random.default_rng(seed)
        out = []
        for _ in range(reps):
            d = GraphDelta()
            d.add_edges(r.integers(0, n, edges_per_commit),
                        r.integers(0, n, edges_per_commit))
            out.append(d)
        return out

    host = StreamingTiledGraph(topo, reserve_frac=0.5,
                               device_arrays=False)
    host.apply(deltas(1)[0])  # warm allocator paths
    t0 = time.perf_counter()
    for d in deltas(2):
        host.apply(d)
    host_s = (time.perf_counter() - t0) / reps
    dev = StreamingTiledGraph(topo, reserve_frac=0.5)
    dev.apply(deltas(1)[0])  # warm the bucketed scatter compiles
    jax.block_until_ready(dev.graph()[1])
    rows_before = dev.stats["tile_rows_swapped"]
    t0 = time.perf_counter()
    for d in deltas(2):
        dev.apply(d)
    jax.block_until_ready(dev.graph()[1])
    total_s = (time.perf_counter() - t0) / reps
    rows_per_commit = (dev.stats["tile_rows_swapped"] - rows_before) / reps
    context["stream_append_s"] = round(host_s / edges_per_commit, 9)
    context["stream_swap_s"] = round(max(total_s - host_s, 0.0), 6)
    context["stream_edges_per_commit"] = edges_per_commit
    context["stream_commit_spills"] = int(dev.stats["tile_spills"])
    log(
        f"stream delta apply: append {context['stream_append_s']*1e6:.2f} "
        f"us/edge, batched device swap "
        f"{context['stream_swap_s']*1e3:.2f} ms/commit "
        f"({edges_per_commit} edges, {rows_per_commit:.0f} tile rows)"
    )

    # round-21 lifecycle legs — the measured inputs of delta_table's
    # churn/compaction terms: delete the just-appended edges (masked lane
    # rewrites on the delete-side dev stream), then one compaction pass
    # over the waste the churn left behind
    del_deltas = deltas(2)  # the same edges the dev stream applied
    t0 = time.perf_counter()
    for d in del_deltas:
        rm = GraphDelta()
        s_arr, d_arr = d.edges()
        rm.remove_edges(s_arr, d_arr)
        dev.apply(rm)
    jax.block_until_ready(dev.graph()[1])
    delete_s = (time.perf_counter() - t0) / reps
    context["stream_delete_s"] = round(delete_s / edges_per_commit, 9)
    t0 = time.perf_counter()
    comp = dev.compact()
    jax.block_until_ready(dev.graph()[1])
    context["stream_compact_s"] = round(time.perf_counter() - t0, 6)
    context["stream_compact_reclaimed"] = int(comp["tiles_reclaimed"])
    log(
        f"stream lifecycle: delete "
        f"{context['stream_delete_s']*1e6:.2f} us/edge, compaction pass "
        f"{context['stream_compact_s']*1e3:.2f} ms "
        f"({comp['tiles_reclaimed']} tile rows reclaimed)"
    )


def bench_workloads(context, n=50_000, deg=8, reps=5):
    """Round-19 workload costs — the MEASURED inputs of
    `scaling.lp_table` and the temporal rows of SCALING.md:

    - ``temporal_draw_s``: one masked tiled temporal draw
      (`ops.sample.tiled_temporal_sample_layer`) at [B=1024, k=8] — the
      marginal cost of the timestamp mask + recency weighting over the
      uniform tiled draw (compare ``sample_layer`` sections).
    - ``temporal_step_s``: one fused temporal serve flush (sample +
      gather + forward + the query-time argument) at bucket 64 through a
      `workloads.TemporalServeEngine` — the t_node_step_s input of
      `lp_table`.
    - ``lp_pair_step_s`` / ``lp_head_s``: measured per-pair cost of a
      64-pair `predict_pairs` batch (cache disabled — the honest
      two-endpoints-per-pair device cost) and the scoring head alone.
    """
    import jax
    import jax.numpy as jnp

    from quiver_tpu import CSRTopo
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.ops.sample import tiled_temporal_sample_layer
    from quiver_tpu.pyg.sage_sampler import GraphSageSampler
    from quiver_tpu.serve import ServeConfig
    from quiver_tpu.workloads import (
        PairHead,
        TemporalServeEngine,
        TemporalTiledGraph,
    )

    rng = np.random.default_rng(29)
    src = np.repeat(np.arange(n, dtype=np.int64), deg)
    dst = rng.integers(0, n, src.shape[0])
    topo = CSRTopo(edge_index=np.stack([src, dst]))
    ts = rng.uniform(0.0, 1000.0, topo.indices.shape[0]).astype(np.float32)
    tg = TemporalTiledGraph(topo, ts)
    bd, tiles, tt = tg.temporal_graph()
    B, k = 1024, 8
    seeds = jnp.asarray(rng.integers(0, n, B))
    valid = jnp.ones((B,), bool)
    tvec = jnp.asarray(rng.uniform(0, 1000, B).astype(np.float32))
    key = jax.random.key(11)
    out = tiled_temporal_sample_layer(
        bd, tiles, tt, seeds, valid, k, key, tvec, max_deg=512, recency=0.01
    )
    jax.block_until_ready(out[0])  # warm the compile
    t0 = time.perf_counter()
    for i in range(reps):
        out = tiled_temporal_sample_layer(
            bd, tiles, tt, seeds, valid, k,
            jax.random.fold_in(key, i), tvec, max_deg=512, recency=0.01,
        )
    jax.block_until_ready(out[0])
    context["temporal_draw_s"] = round((time.perf_counter() - t0) / reps, 6)

    dim, bucket = 64, 64
    feat = rng.standard_normal((n, dim)).astype(np.float32)
    model = GraphSAGE(hidden_dim=64, out_dim=32, num_layers=2, dropout=0.0)
    smp = GraphSageSampler(topo, sizes=[8, 8], mode="TPU", seed=7,
                           dedup=False)
    smp.bind_temporal(tg, recency=0.01)
    init_ds = GraphSageSampler(
        topo, sizes=[8, 8], mode="TPU", seed=7, dedup=False
    ).bind_temporal(tg, recency=0.01).sample_dense(
        np.arange(bucket, dtype=np.int64), t=1e9
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((init_ds.n_id.shape[0], dim)),
        init_ds.adjs,
    )
    eng = TemporalServeEngine(
        model, params, smp, feat,
        ServeConfig(max_batch=bucket, buckets=(bucket,), max_delay_ms=1e9,
                    cache_entries=0),
        t_quantum=0.0, pair_head=PairHead("dot"),
    )
    eng.warmup()
    nodes = rng.integers(0, n, (reps + 1, bucket))
    times = rng.uniform(0, 1000, (reps + 1, bucket))
    eng.predict(nodes[0], t=times[0])  # warm
    t0 = time.perf_counter()
    for i in range(1, reps + 1):
        eng.predict(nodes[i], t=times[i])
    context["temporal_step_s"] = round((time.perf_counter() - t0) / reps, 6)

    pairs = rng.integers(0, n, (reps + 1, bucket // 2, 2))
    eng.predict_pairs(pairs[0], t=500.0)  # warm (head compile included)
    t0 = time.perf_counter()
    for i in range(1, reps + 1):
        eng.predict_pairs(pairs[i], t=float(times[i][0]))
    per_batch = (time.perf_counter() - t0) / reps
    context["lp_pair_step_s"] = round(per_batch / (bucket // 2), 8)
    head = eng.pair_head
    hu = rng.standard_normal((bucket // 2, 32)).astype(np.float32)
    hv = rng.standard_normal((bucket // 2, 32)).astype(np.float32)
    head.score(hu, hv)  # warm
    t0 = time.perf_counter()
    for _ in range(reps):
        head.score(hu, hv)
    context["lp_head_s"] = round(
        (time.perf_counter() - t0) / reps / (bucket // 2), 9
    )
    log(
        f"workloads: temporal draw {context['temporal_draw_s']*1e3:.2f} "
        f"ms/call@{B}, fused temporal step "
        f"{context['temporal_step_s']*1e3:.2f} ms@{bucket}, LP pair "
        f"{context['lp_pair_step_s']*1e6:.1f} us/pair (head "
        f"{context['lp_head_s']*1e9:.0f} ns/pair)"
    )


def bench_tier_rows(context, n=8192, dim=100, reps=5):
    """Round-14 per-row tier gather costs — the MEASURED inputs of
    `scaling.tier_table` (``tier_hbm_row_s`` / ``tier_host_row_s`` /
    ``tier_disk_row_s``): one adaptive `tiers.TierStore` over a synthetic
    [n, dim] table, a 256-row gather timed per tier. The disk number is
    the POOLED flat-file read on this box's page cache — real cold
    storage is slower; `scripts/serve_probe.py --tiers` carries the
    simulated-latency comparison, this leg prices the mechanism."""
    import tempfile

    from quiver_tpu.pipeline import AsyncReadPool
    from quiver_tpu.tiers import TIER_DISK, TIER_HBM, TIER_HOST, TierStore

    rng = np.random.default_rng(17)
    arr = rng.standard_normal((n, dim)).astype(np.float32)
    store = TierStore.build(
        arr, os.path.join(tempfile.mkdtemp(prefix="qt_bench_tiers_"), "t"),
        hbm_rows=n // 8, host_rows=n // 4,
        read_pool=AsyncReadPool(4, chunk_rows=128),
    )

    for tier, key in ((TIER_HBM, "tier_hbm_row_s"),
                      (TIER_HOST, "tier_host_row_s"),
                      (TIER_DISK, "tier_disk_row_s")):
        res = store.placement.residents(tier)
        batch = np.tile(res, -(-256 // max(res.size, 1)))[:256]
        np.asarray(store.gather(batch))  # warm (compile + page cache)
        t0 = time.perf_counter()
        for _ in range(reps):
            np.asarray(store.gather(batch))
        context[key] = (time.perf_counter() - t0) / reps / batch.size
    # tier_table's disk input is the SINGLE-THREAD read cost (the model
    # divides by the pool width itself); measure it on the bare backing
    # read, no pool in the loop
    disk_ids = store.placement.residents(TIER_DISK)[:256]
    store.backing.read_block(disk_ids)  # warm page cache
    t0 = time.perf_counter()
    for _ in range(reps):
        store.backing.read_block(disk_ids)
    context["tier_disk_row_single_s"] = (
        (time.perf_counter() - t0) / reps / disk_ids.size
    )
    log(
        "tier per-row gather: hbm "
        f"{context['tier_hbm_row_s']*1e6:.2f} us, host "
        f"{context['tier_host_row_s']*1e6:.2f} us, disk(pooled page-cache) "
        f"{context['tier_disk_row_s']*1e6:.2f} us, disk(single-thread) "
        f"{context['tier_disk_row_single_s']*1e6:.2f} us"
    )
    # round-18 flush-ahead staging costs: what a PREFETCHED disk row
    # costs the gather (issue ahead, reads land, take() consumes from
    # DRAM) vs the same rows read in-path. The consume number is why
    # `scaling.tier_table(prefetch_hit_rate=)` prices staged rows near
    # host_row_s — the backing read happened off the critical path.
    pf = store.enable_prefetch(max_rows=4096)
    batch = store.placement.residents(TIER_DISK)[:256]
    for _ in range(2):  # warm: thread-local fds/buffers + code paths
        store.prefetch_rows(batch)
        while len(pf):
            pf.take(batch)
    t_issue = t_take = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        store.prefetch_rows(batch)
        t_issue += time.perf_counter() - t0
        time.sleep(0.01)  # let the pool land the reads (the hidden part)
        t0 = time.perf_counter()
        pos, rows = pf.take(batch)
        t_take += time.perf_counter() - t0
        assert pos.shape[0] == batch.size
    context["tier_prefetch_issue_row_s"] = t_issue / reps / batch.size
    context["tier_prefetch_consume_row_s"] = t_take / reps / batch.size
    log(
        "tier prefetch staging: issue "
        f"{context['tier_prefetch_issue_row_s']*1e6:.2f} us/row, consume "
        f"{context['tier_prefetch_consume_row_s']*1e6:.2f} us/row "
        "(vs the in-path pooled disk read above)"
    )


def bench_tiered_pipeline(
    context, indptr_np, indices_np, caps, batches=4, batch=1024, dim=100, classes=47
):
    """Overlap evidence for the tiered path (round-2 verdict item 4): run
    the REAL double-buffered `TrainPipeline` on the 20%-hot config and
    report how much of the cold-tier (host gather + H2D) latency the
    prefetch hides, at depth 1 and 2, next to the raw link H2D rate that
    bounds ANY cold-tier number in this environment (axon tunnel ~0.06
    GB/s; a TPU VM's PCIe link is ~100x that, reference CPU baseline
    1.27 GB/s, Introduction_en.md:94)."""
    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import CSRTopo, Feature
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.pipeline import (
        TieredFeaturePipeline,
        TrainPipeline,
        make_tiered_train_step,
    )
    from quiver_tpu.pyg import GraphSageSampler

    # raw link H2D: 64 MB up, dependent fetch ends the clock
    buf = np.ones((16 << 20,), np.float32)
    t0 = time.time()
    d = jax.device_put(buf)
    float(d[-1])
    h2d_gbps = buf.nbytes / (time.time() - t0) / 1e9
    context["h2d_gbps"] = round(h2d_gbps, 3)
    log(f"link H2D: {h2d_gbps:.3f} GB/s (hard bound for any cold-tier rate here)")

    topo = CSRTopo(indptr=indptr_np, indices=indices_np)
    n_nodes = topo.node_count
    rng = np.random.default_rng(5)
    table_host = rng.standard_normal((n_nodes, dim)).astype(np.float32)
    hot_rows = n_nodes // 5
    feat = Feature(
        rank=0, device_list=[0],
        device_cache_size=hot_rows * dim * 4, csr_topo=topo,
    )
    feat.from_cpu_tensor(table_host)
    sampler = GraphSageSampler(topo, sizes=[15, 10, 5], mode="TPU", caps=caps)
    labels = jax.jit(
        lambda k: jax.random.randint(k, (n_nodes,), 0, classes, jnp.int32)
    )(jax.random.key(8))
    model = GraphSAGE(hidden_dim=256, out_dim=classes, num_layers=3, dropout=0.0)
    tx = optax.adam(1e-3)
    pipe = TieredFeaturePipeline(feat)
    step_fn = make_tiered_train_step(model, tx, labels, pipe.hot_table)

    seed_batches = [
        rng.integers(0, n_nodes, batch).astype(np.int32) for _ in range(batches)
    ]
    tp = TrainPipeline(sampler, feat, step_fn, depth=1, tiered=pipe)
    # bootstrap params + compile the step off the clock
    b0 = tp._stage(seed_batches[0])
    from quiver_tpu.pipeline import tiered_lookup

    x0 = tiered_lookup(pipe.hot_table, b0.mapped, b0.cold_rows, b0.cold_pos)
    params = model.init(jax.random.key(1), x0, b0.ds.adjs)
    opt_state = tx.init(params)
    _p, _o, l0 = step_fn(params, opt_state, jax.random.key(2), b0)
    float(l0)

    # sequential reference: stage fully, then step fully, per batch
    stage_s = step_s = 0.0
    cold0 = tp.tiered.cold_rows_seen
    for s in seed_batches:
        t0 = time.time()
        b = tp._stage(s)
        float(b.cold_rows.sum()) if b.cold_rows.shape[0] else None  # sync H2D
        stage_s += time.time() - t0
        t0 = time.time()
        params, opt_state, loss = step_fn(params, opt_state, jax.random.key(3), b)
        float(loss)
        step_s += time.time() - t0
    cold_per_batch = (tp.tiered.cold_rows_seen - cold0) / batches
    seq_s = stage_s + step_s

    pipe_s = {}
    stats_by_depth = {}
    for depth in (1, 2):
        # timed epochs run UNINSTRUMENTED: measure_overlap syncs each
        # step's loss (one ~0.1 s D2H per step on this tunnel) inside the
        # window — the async pipeline being benchmarked pays no such cost
        tp_d = TrainPipeline(sampler, feat, step_fn, depth=depth, tiered=pipe)
        t0 = time.time()
        params, opt_state, losses = tp_d.run_epoch(
            seed_batches, params, opt_state, jax.random.key(4)
        )
        pipe_s[depth] = time.time() - t0
        stats_by_depth[depth] = tp_d.stats
    best = min(pipe_s.values())
    best_depth = min(pipe_s, key=pipe_s.get)
    # MEASURED overlap evidence. Preferred: a separate instrumented epoch
    # whose "step" spans cover device execution (its per-step syncs stay
    # outside every timed window above). Fallback when the budget is
    # gone: the uninstrumented runs' spans — the three HOST stages are
    # fully measured there, only the step span is dispatch-only.
    step_spans = "dispatch-only"
    ov = stats_by_depth[best_depth].overlap_summary()
    if remaining() > 60:
        tp_m = TrainPipeline(
            sampler, feat, step_fn, depth=best_depth, tiered=pipe,
            measure_overlap=True,
        )
        params, opt_state, _ = tp_m.run_epoch(
            seed_batches, params, opt_state, jax.random.key(5)
        )
        ov = tp_m.stats.overlap_summary()
        step_spans = "execution"
    else:
        log("budget exhausted before instrumented overlap epoch; "
            "reporting host-stage spans from the timed runs")
    w = int(b0.mapped.shape[0])
    gbps_pipe = batches * w * dim * 4 / best / 1e9
    # the floor the LINK imposes: the cold bytes must cross the tunnel no
    # matter what; everything above that floor is hideable latency
    cold_bytes = cold_per_batch * dim * 4
    link_floor_s = batches * cold_bytes / max(h2d_gbps, 1e-9) / 1e9
    bound_gbps = batches * w * dim * 4 / link_floor_s / 1e9 if cold_bytes else float("inf")
    # fraction of the NON-link latency (sync RPCs, host gather, device step,
    # sampling) the prefetch hides: 1.0 = the pipelined wall is pure link
    hideable_s = max(seq_s - link_floor_s, 1e-9)
    hidden_frac = min(max((seq_s - best) / hideable_s, 0.0), 1.0)
    link_eff = min(link_floor_s / best, 1.0) if best > 0 else 0.0
    log(
        f"tiered pipeline: stage {stage_s/batches*1e3:.0f} ms + step "
        f"{step_s/batches*1e3:.0f} ms seq -> pipe d1 {pipe_s[1]/batches*1e3:.0f} ms, "
        f"d2 {pipe_s[2]/batches*1e3:.0f} ms/batch; {hidden_frac:.0%} of non-link "
        f"latency hidden (link efficiency {link_eff:.0%}); {gbps_pipe:.2f} GB/s "
        f"delivered (link-bound ceiling {bound_gbps:.2f} GB/s at "
        f"{cold_per_batch:.0f} cold rows/batch)"
    )
    context["tiered_cold_rows_per_batch"] = round(cold_per_batch, 1)
    context["tiered_stage_s_per_batch"] = round(stage_s / batches, 3)
    context["tiered_step_s_per_batch"] = round(step_s / batches, 3)
    context["tiered_pipe_s_per_batch_d1"] = round(pipe_s[1] / batches, 3)
    context["tiered_pipe_s_per_batch_d2"] = round(pipe_s[2] / batches, 3)
    context["tiered_hidden_frac"] = round(hidden_frac, 3)
    context["tiered_link_efficiency"] = round(link_eff, 3)
    context["feature_tiered20_pipe_gbps"] = round(gbps_pipe, 3)
    context["tiered_link_bound_gbps"] = round(bound_gbps, 3)
    # MEASURED overlap (one monotonic clock over the pipelined run itself;
    # round-4 verdict item 3 — the seq-minus-pipe subtraction above leans
    # on a separately-timed link probe and drifts with tunnel state):
    # overlap_frac = fraction of the covered wall with >= 2 stages active;
    # hidden_frac_measured = share of total stage busy-time hidden under
    # another stage (0 = serial; 0.75 = four stages perfectly stacked)
    if ov:
        log(
            f"tiered pipeline measured overlap (depth {best_depth}, step "
            f"spans {step_spans}): >=2 stages active "
            f"{ov['overlap_frac']:.0%} of wall; "
            f"{ov['hidden_frac_measured']:.0%} of stage busy-time hidden; "
            f"busy {ov['busy_s']}"
        )
        context["tiered_overlap_measured"] = ov["overlap_frac"]
        context["tiered_hidden_frac_measured"] = ov["hidden_frac_measured"]
        context["tiered_stage_busy_s"] = ov["busy_s"]
        context["tiered_overlap_step_spans"] = step_spans


def bench_serve(context, indptr_np, indices_np, table, caps, n_requests=256):
    """Online serving engine (`quiver_tpu.serve`) on the products graph:
    closed-loop Zipfian replay through the REAL micro-batcher + coalescer +
    embedding cache, at two skews x in-flight window 1 (serial) and 2
    (pipelined, two client threads + pollers; measured per-stage overlap
    from `stats.spans`). One fixed bucket (64) keeps this to ONE compile,
    pre-traced by `engine.warmup()`; the per-dispatch RPC floor
    (`context["rpc_floor_s"]`) bounds every latency number in this tunneled
    environment — read the hit-rate / coalescing / dispatch-count /
    overlap columns as the hardware-true signal and the QPS as a floor (a
    co-located host skips the tunnel entirely).

    Also measures the serve dispatch cost SPLIT (NEXT.md follow-up b):
    `inference.sample_batch` vs `inference.forward_logits` at the serve
    bucket, recorded as ``serve_sample_s`` / ``serve_forward_s`` so
    `scripts/scaling_model.py --bench` prices `scaling.serve_table` with
    the eval-shaped cost instead of the pessimistic TRAIN-step bound."""
    import threading

    import jax
    import jax.numpy as jnp

    from quiver_tpu import CSRTopo
    from quiver_tpu.inference import _cached_apply, time_eval_split
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.pyg import GraphSageSampler
    from quiver_tpu.serve import ServeConfig, ServeEngine, zipfian_trace

    topo = CSRTopo(indptr=indptr_np, indices=indices_np)
    n_nodes = topo.node_count
    model = GraphSAGE(hidden_dim=256, out_dim=47, num_layers=3, dropout=0.0)

    def make_sampler():
        return GraphSageSampler(
            topo, sizes=[15, 10, 5], mode="TPU", caps=caps, seed=11
        )

    s0 = make_sampler()
    ds0 = s0.sample_dense(np.arange(64, dtype=np.int64))
    params = model.init(
        jax.random.key(3),
        jnp.zeros((ds0.n_id.shape[0], table.shape[1]), jnp.float32),
        ds0.adjs,
    )

    # eval-shaped dispatch cost split at the serve bucket: the two stages
    # of batch_logits timed separately (shared helper with serve_probe so
    # the two artifacts use one methodology; the RPC floor bounds both
    # legs the same way it bounds every number here)
    apply = _cached_apply(model)
    t_sample, t_forward = time_eval_split(
        apply, params, make_sampler(), table, np.arange(64, dtype=np.int64)
    )
    context["serve_sample_s"] = round(t_sample, 6)
    context["serve_forward_s"] = round(t_forward, 6)
    context["serve_eval_ref_batch"] = 64
    log(
        f"serve dispatch split @64: sample {t_sample*1e3:.1f} ms + forward "
        f"{t_forward*1e3:.1f} ms (eval-shaped serve_table inputs)"
    )

    # fused ONE-dispatch step at the same bucket (round 11): the whole
    # sample+gather+forward as one pre-bound executable — its delta vs the
    # split sum is the per-flush overhead the 2->1 cut removes (on the
    # tunnel that is a whole extra RPC floor per flush)
    try:
        timer_eng = ServeEngine(
            model, params, make_sampler(), table,
            ServeConfig(max_batch=64, buckets=(64,)),
        )
        if timer_eng._programs is None:
            raise TypeError("engine fell back to the split path")
        timer_eng.warmup()
        twin = make_sampler()
        seeds64 = np.arange(64, dtype=np.int64)
        np.asarray(timer_eng._programs(64, params, twin.next_key(), seeds64))
        t0 = time.time()
        for _ in range(10):
            out = timer_eng._programs(64, params, twin.next_key(), seeds64)
        np.asarray(out)
        t_fused = (time.time() - t0) / 10
        context["serve_path"] = "fused"
        context["serve_fused_step_s"] = round(t_fused, 6)
        context["serve_split_minus_fused_s"] = round(
            max(t_sample + t_forward - t_fused, 0.0), 6
        )
        log(
            f"serve fused one-dispatch @64: {t_fused*1e3:.1f} ms "
            f"(split sum {(t_sample + t_forward)*1e3:.1f} ms; delta = "
            "per-flush overhead the 2->1 cut removes)"
        )
    except TypeError as exc:
        # fused path unavailable on this config (tiered table, HOST
        # sampler): record the path honestly, no fused keys
        context["serve_path"] = "split"
        log(f"serve path: split ({exc})")
    except Exception as exc:
        context["serve_fused_step_error"] = repr(exc)
        log(f"serve fused step timing failed: {exc}")

    # host submit path (round 20): scalar-loop vs batch `submit_many`
    # admission cost, flushes deferred past the timed window and the
    # cache off — the bench.py counterpart of scripts/bench_frontend.py
    # (FRONTEND_r01.json), so a bench artifact alone carries the inputs
    # that price `scaling.serve_table(host_submit_us=)`
    try:
        from quiver_tpu.serve.engine import abandon_undrained

        htrace = zipfian_trace(n_nodes, 4096, alpha=0.99, seed=23)
        hwalls = {}
        for batched in (False, True):
            heng = ServeEngine(
                model, params, make_sampler(), table,
                ServeConfig(max_batch=1 << 13, max_delay_ms=1e9,
                            cache_entries=0),
            )
            t0 = time.time()
            if batched:
                heng.submit_many(htrace)
            else:
                for nid in htrace:
                    heng.submit(int(nid))
            hwalls[batched] = time.time() - t0
            abandon_undrained(heng, drained=False)
        context["host_submit_scalar_us"] = round(
            hwalls[False] / htrace.shape[0] * 1e6, 3
        )
        context["host_submit_batch_us"] = round(
            hwalls[True] / htrace.shape[0] * 1e6, 3
        )
        # the canonical key scaling_model.py --frontend reads from a
        # FRONTEND artifact; same name here for a uniform pickup
        context["host_submit_us"] = context["host_submit_batch_us"]
        log(
            f"host submit path @4096: scalar "
            f"{context['host_submit_scalar_us']:.1f} us/req, batch "
            f"{context['host_submit_batch_us']:.1f} us/req "
            f"({hwalls[False] / max(hwalls[True], 1e-12):.1f}x)"
        )
    except Exception as exc:
        context["host_submit_error"] = repr(exc)
        log(f"host submit timing failed: {exc}")

    # host drain path (round 22): the resolve/delivery half — dispatch
    # mocked to canned read-only logits so the timed wall is host work
    # only (assemble + seal + block resolve, then `results_many`). The
    # bench.py counterpart of FRONTEND_r02.json's host_resolve_us /
    # host_deliver_us keys, so a bench artifact alone carries both
    # inputs of `scaling.serve_table(host_submit_us=, host_resolve_us=)`
    try:
        htrace = zipfian_trace(n_nodes, 4096, alpha=0.99, seed=23)
        heng = ServeEngine(
            model, params, make_sampler(), table,
            ServeConfig(max_batch=1 << 13, max_delay_ms=1e9,
                        cache_entries=0),
        )
        canned = np.zeros((1 << 13, model.out_dim), np.float32)
        canned.setflags(write=False)

        def _mock_dispatch(fl, _eng=heng, _c=canned):
            with _eng._lock:
                _eng.stats.dispatch_calls += 1
                _eng.stats.execute_calls += 1
            return _c

        heng._dispatch = _mock_dispatch
        handles = heng.submit_many(htrace)
        t0 = time.time()
        while heng._drainable():
            heng.flush()
        drain_wall = time.time() - t0
        t0 = time.time()
        heng.results_many(handles)
        deliver_wall = time.time() - t0
        context["host_resolve_us"] = round(
            drain_wall / htrace.shape[0] * 1e6, 3
        )
        context["host_deliver_us"] = round(
            deliver_wall / htrace.shape[0] * 1e6, 3
        )
        log(
            f"host drain path @4096 (mocked dispatch): resolve "
            f"{context['host_resolve_us']:.2f} us/req, deliver "
            f"{context['host_deliver_us']:.2f} us/req"
        )
    except Exception as exc:
        context["host_resolve_error"] = repr(exc)
        log(f"host drain timing failed: {exc}")

    for alpha in (0.0, 0.99):
        for mif in (1, 2):
            eng = ServeEngine(
                model, params, make_sampler(), table,
                ServeConfig(max_batch=64, buckets=(64,), max_delay_ms=2.0,
                            cache_entries=1 << 16, max_in_flight=mif),
            )
            eng.warmup()  # pre-trace the bucket off the clock (twin sampler)
            eng.cache.invalidate()
            eng.reset_stats()
            trace = zipfian_trace(n_nodes, n_requests, alpha=alpha, seed=17)
            t0 = time.time()
            client_errors = []
            if mif == 1:
                eng.predict(trace)  # round-8 closed loop, unchanged
            else:
                # saturated pipelined load: two closed-loop clients + the
                # engine's pollers keep up to 2 flushes in flight. Client
                # exceptions are captured, not dropped — a timed-out or
                # failed trace must not record a plausible-looking QPS row
                chunks = np.array_split(trace, 2)

                def client(c):
                    try:
                        eng.predict(c, 600)
                    except Exception as exc:
                        client_errors.append(repr(exc))

                with eng:
                    ts = [threading.Thread(target=client, args=(c,)) for c in chunks]
                    [t.start() for t in ts]
                    [t.join() for t in ts]
            wall = time.time() - t0
            if client_errors:
                context[f"serve_zipf{alpha:g}_mif{mif}_errors"] = client_errors
                log(f"serve zipf={alpha} mif={mif} FAILED: {client_errors}")
                continue
            s = eng.stats
            lat = s.latency.snapshot()
            key = f"serve_zipf{alpha:g}" + ("" if mif == 1 else f"_mif{mif}")
            context[f"{key}_qps"] = round(n_requests / wall, 1)
            context[f"{key}_p50_ms"] = round(lat["p50_ms"], 2)
            context[f"{key}_p95_ms"] = round(lat["p95_ms"], 2)
            context[f"{key}_p99_ms"] = round(lat["p99_ms"], 2)
            context[f"{key}_cache_hit_rate"] = round(s.cache.hit_rate, 4)
            context[f"{key}_dispatches"] = s.dispatches
            context[f"{key}_execute_calls"] = s.execute_calls
            context[f"{key}_late_admitted"] = s.late_admitted
            context[f"{key}_coalesced"] = s.coalesced
            ov = s.spans.overlap_summary()
            if mif > 1:
                context[f"{key}_overlap_frac"] = ov.get("overlap_frac", 0.0)
                context[f"{key}_inflight_peak"] = s.inflight_peak
            log(
                f"serve zipf={alpha} mif={mif}: {n_requests / wall:.0f} QPS, "
                f"p50/p95/p99 {lat['p50_ms']:.1f}/{lat['p95_ms']:.1f}/"
                f"{lat['p99_ms']:.1f} ms, hit rate {s.cache.hit_rate:.0%}, "
                f"{s.dispatches} dispatches, {s.coalesced} coalesced"
                + (f", overlap {ov.get('overlap_frac', 0.0):.0%}" if mif > 1 else "")
            )

    # observability cost on the saturated leg (round 12, ISSUE 7): the
    # same mif=2 threaded-client run with the request-lifecycle journal +
    # metrics registry ON vs OFF, median-of-3 INTERLEAVED (off/on pairs
    # back to back, so box drift hits both sides equally). The journal is
    # designed to be left on in production; this is the measured price.
    def _run_saturated(journal_events, workload=None):
        eng = ServeEngine(
            model, params, make_sampler(), table,
            ServeConfig(max_batch=64, buckets=(64,), max_delay_ms=2.0,
                        cache_entries=1 << 16, max_in_flight=2,
                        journal_events=journal_events, workload=workload),
        )
        eng.warmup()
        if journal_events or workload is not None:
            eng.register_metrics()  # passive adapters live during the run
        eng.cache.invalidate()
        eng.reset_stats()
        trace = zipfian_trace(n_nodes, n_requests, alpha=0.99, seed=23)
        chunks = np.array_split(trace, 2)
        errs = []

        def client(c):
            try:
                eng.predict(c, 600)
            except Exception as exc:
                errs.append(repr(exc))

        t0 = time.time()
        with eng:
            ts = [threading.Thread(target=client, args=(c,)) for c in chunks]
            [t.start() for t in ts]
            [t.join() for t in ts]
        wall = time.time() - t0
        if errs:
            raise RuntimeError(errs)
        return n_requests / wall

    try:
        qps_obs_on, qps_obs_off = [], []
        for _ in range(3):
            qps_obs_off.append(round(_run_saturated(0), 1))
            qps_obs_on.append(round(_run_saturated(1 << 16), 1))
        med_on = sorted(qps_obs_on)[1]
        med_off = sorted(qps_obs_off)[1]
        context["serve_obs_qps_on"] = qps_obs_on
        context["serve_obs_qps_off"] = qps_obs_off
        context["serve_obs_overhead_frac"] = round(1.0 - med_on / med_off, 4)
        log(
            f"serve obs overhead: on {med_on:.0f} vs off {med_off:.0f} QPS "
            f"(median-of-3) -> frac {context['serve_obs_overhead_frac']:+.4f} "
            f"(spread on {min(qps_obs_on):.0f}-{max(qps_obs_on):.0f}, "
            f"off {min(qps_obs_off):.0f}-{max(qps_obs_off):.0f})"
        )
    except Exception as exc:
        context["serve_obs_overhead_error"] = repr(exc)
        log(f"serve obs overhead leg failed: {exc}")

    # workload-sketch cost on the same saturated leg (round 13, ISSUE 8):
    # frequency sketches + owner stats + cache taps ON vs OFF, the same
    # interleaved median-of-3 shape as the journal leg above — the
    # measured price of leaving the access-skew measurement on in
    # production (ROADMAP items 2/3 read the sketch; this is what reading
    # it costs).
    try:
        from quiver_tpu.trace import WorkloadConfig

        qps_skew_on, qps_skew_off = [], []
        for _ in range(3):
            qps_skew_off.append(round(_run_saturated(0), 1))
            qps_skew_on.append(round(
                _run_saturated(0, workload=WorkloadConfig(topk=256)), 1
            ))
        med_on = sorted(qps_skew_on)[1]
        med_off = sorted(qps_skew_off)[1]
        context["serve_skew_qps_on"] = qps_skew_on
        context["serve_skew_qps_off"] = qps_skew_off
        context["serve_skew_overhead_frac"] = round(1.0 - med_on / med_off, 4)
        log(
            f"serve workload-sketch overhead: on {med_on:.0f} vs off "
            f"{med_off:.0f} QPS (median-of-3) -> frac "
            f"{context['serve_skew_overhead_frac']:+.4f} "
            f"(spread on {min(qps_skew_on):.0f}-{max(qps_skew_on):.0f}, "
            f"off {min(qps_skew_off):.0f}-{max(qps_skew_off):.0f})"
        )
    except Exception as exc:
        context["serve_skew_overhead_error"] = repr(exc)
        log(f"serve workload-sketch overhead leg failed: {exc}")

    # distributed serving (round 10): seed-ownership routed engine at
    # hosts=2 over the SAME graph, exchange='host' (one chip — the hops
    # are host-side here; the collective leg is covered by the CPU-tier
    # probe and the 2-process harness). The hardware-true signal on this
    # box is the per-shard sub-batch width (~half the router flush), the
    # shard edge fraction (halo included, honestly), and in-run replay
    # parity; QPS shares one chip so it is a routing-overhead floor, not
    # a scaling number.
    try:
        from quiver_tpu.serve import (
            DistServeConfig, DistServeEngine, replay_shard_oracle,
        )

        dist = DistServeEngine.build(
            model, params, topo, table, [15, 10, 5], hosts=2,
            config=DistServeConfig(
                hosts=2, max_batch=64, max_delay_ms=2.0, exchange="host",
                record_dispatches=True,
                shard_config=ServeConfig(
                    max_batch=64, buckets=(64,), max_delay_ms=2.0,
                    record_dispatches=True,
                ),
            ),
            sampler_seed=11, sampler_kw={"caps": caps},
        )
        dist.warmup()
        dist.reset_stats()
        n_dist = min(n_requests, 96)
        trace = zipfian_trace(n_nodes, n_dist, alpha=0.99, seed=19)
        t0 = time.time()
        out = dist.predict(trace)
        wall = time.time() - t0
        oracle = replay_shard_oracle(dist, model, params, make_sampler, table)
        parity = all(
            np.array_equal(out[i], oracle[int(nid)]) for i, nid in enumerate(trace)
        )
        sd = dist.stats
        context["serve_dist2_qps"] = round(n_dist / wall, 1)
        context["serve_dist2_parity"] = parity
        context["serve_dist2_router_dispatches"] = sd.router_dispatches
        context["serve_dist2_mean_sub_batch_width"] = {
            str(h): round(w, 2) for h, w in sd.mean_sub_batch_width().items()
        }
        context["serve_dist2_edge_frac"] = {
            str(h): round(st["edge_frac"], 4)
            for h, st in dist.shard_topo_stats.items()
        }
        log(
            f"serve dist hosts=2: {n_dist / wall:.0f} QPS (1-chip floor), "
            f"widths {context['serve_dist2_mean_sub_batch_width']}, "
            f"edge frac {context['serve_dist2_edge_frac']}, parity={parity}"
        )
        if not parity:
            log("serve dist PARITY VIOLATION — investigate before trusting r10")
    except Exception as exc:
        context["serve_dist2_error"] = repr(exc)
        log(f"serve dist bench failed: {exc}")

    # fleet robustness (round 15, ISSUE 10): the SAME hosts=2 routed
    # engine with a deterministic owner-kill injected mid-run and the
    # full-graph fallback absorbing — measures what serving through the
    # failover path costs (hedged QPS vs the healthy serve_dist2_qps
    # above) and asserts in-run that every completed row still bit-matches
    # the offline fleet replay. A fault leg that ran means the numbers are
    # from a run where the parity held.
    try:
        from quiver_tpu.serve import (
            DistServeConfig, DistServeEngine, FaultInjector, FaultSpec,
            replay_fleet_oracle,
        )

        inj = FaultInjector([FaultSpec(owner=0, fid=2, kind="kill")])
        dist = DistServeEngine.build(
            model, params, topo, table, [15, 10, 5], hosts=2,
            config=DistServeConfig(
                hosts=2, max_batch=64, max_delay_ms=2.0, exchange="host",
                record_dispatches=True, fault_injector=inj,
                full_graph_fallback=True, eject_after=1,
                eject_backoff_flushes=8,
                shard_config=ServeConfig(
                    max_batch=64, buckets=(64,), max_delay_ms=2.0,
                    record_dispatches=True,
                ),
            ),
            sampler_seed=11, sampler_kw={"caps": caps},
        )
        dist.warmup()
        dist.reset_stats()
        n_dist = min(n_requests, 96)
        trace = zipfian_trace(n_nodes, n_dist, alpha=0.99, seed=19)
        t0 = time.time()
        out = dist.predict(trace)
        wall = time.time() - t0
        oracle = replay_fleet_oracle(dist, model, params, make_sampler, table)
        parity = all(
            any(np.array_equal(out[i], c) for c in oracle[int(nid)])
            for i, nid in enumerate(trace)
        )
        sd = dist.stats
        context["serve_hedge_qps"] = round(n_dist / wall, 1)
        context["serve_hedge_parity"] = parity
        context["serve_hedge_hedges"] = sd.hedges
        context["serve_hedge_owner_ejections"] = sd.owner_ejections
        context["serve_hedge_request_errors"] = sd.request_errors
        log(
            f"serve hedged (owner 0 killed @fid 2): {n_dist / wall:.0f} QPS "
            f"through the fallback, hedges {sd.hedges}, ejections "
            f"{sd.owner_ejections}, parity={parity}"
        )
        if not parity:
            log("serve hedge PARITY VIOLATION — investigate before trusting r15")
    except Exception as exc:
        context["serve_hedge_error"] = repr(exc)
        log(f"serve hedge bench failed: {exc}")

    # elastic fleet (round 16, ISSUE 11): the cost of LIVE resharding on
    # the bench graph — wall per bounded migration batch for a 1->2 scale
    # (closure BFS + feature materialization + AOT warmup + the fenced
    # flip; the fence itself holds only for the flip), and in-run oracle
    # parity of a wave served right after the ramp
    try:
        from quiver_tpu.serve import (
            DistServeConfig, DistServeEngine, replay_fleet_oracle,
        )

        dist = DistServeEngine.build(
            model, params, topo, table, [15, 10, 5], hosts=1,
            config=DistServeConfig(
                hosts=1, max_batch=64, max_delay_ms=2.0, exchange="host",
                record_dispatches=True,
                migrate_batch_seeds=max(n_nodes // 4, 1),
                shard_config=ServeConfig(
                    max_batch=64, buckets=(64,), max_delay_ms=2.0,
                    record_dispatches=True,
                ),
            ),
            sampler_seed=11, sampler_kw={"caps": caps},
        )
        dist.warmup()
        dist.reset_stats()
        t0 = time.time()
        summary = dist.scale(2)
        wall = time.time() - t0
        n_dist = min(n_requests, 96)
        trace = zipfian_trace(n_nodes, n_dist, alpha=0.99, seed=19)
        out = dist.predict(trace)
        oracle = replay_fleet_oracle(dist, model, params, make_sampler, table)
        parity = all(
            any(np.array_equal(out[i], c) for c in oracle[int(nid)])
            for i, nid in enumerate(trace)
        )
        context["serve_migrate_batches"] = summary["batches"]
        context["serve_migrate_batch_s"] = round(
            wall / max(summary["batches"], 1), 6
        )
        context["serve_scale_parity"] = parity
        log(
            f"serve scale 1->2: {summary['batches']} migration batches, "
            f"{context['serve_migrate_batch_s']:.3f} s/batch "
            f"(build outside the fence), parity={parity}"
        )
        if not parity:
            log("serve scale PARITY VIOLATION — investigate before trusting r16")
    except Exception as exc:
        context["serve_scale_error"] = repr(exc)
        log(f"serve scale bench failed: {exc}")


def wait_for_backend(max_wait_s=None):
    """The axon tunnel can be down for stretches (observed: hours). Probe
    backend health in a SUBPROCESS (in-process init failures are cached by
    jax) and wait up to QUIVER_BENCH_BACKEND_WAIT_S (default 240 s) before
    giving up — returning False rather than crashing, so the caller can
    still emit a JSON record."""
    import subprocess
    import sys as _sys

    if max_wait_s is None:
        max_wait_s = float(os.environ.get("QUIVER_BENCH_BACKEND_WAIT_S", "240"))
    t0 = time.time()
    attempt = 0
    while True:
        attempt += 1
        err = "?"
        try:
            r = subprocess.run(
                [_sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True, text=True,
                timeout=max(min(90, max_wait_s), 10),
            )
            if r.returncode == 0:
                return True
            if r.stderr:
                err = r.stderr.strip().splitlines()[-1]
        except subprocess.TimeoutExpired:
            err = "probe timed out"
        waited = time.time() - t0
        if waited >= max_wait_s:
            log(f"backend unavailable after {waited:.0f}s ({attempt} probes); "
                f"last error: {err}")
            return False
        log(f"backend not ready (probe {attempt}: {err}), retrying...")
        time.sleep(min(30, max_wait_s - waited))


def main():
    enable_compile_cache()
    if not wait_for_backend():
        print(
            json.dumps(
                {
                    "metric": "neighbor_sampling_throughput",
                    "value": 0.0,
                    "unit": "sampled_edges_per_sec",
                    "vs_baseline": 0.0,
                    "context": {"error": "accelerator backend unavailable"},
                }
            )
        )
        return
    import jax
    import jax.numpy as jnp

    batch = 1024
    n_nodes = 2_449_029

    indptr_np, indices_np = build_graph(n_nodes=n_nodes)
    # graph arrays are jit ARGUMENTS, not closure constants: embedding a
    # 61M-element array as an XLA constant costs ~2 minutes of compile
    t0 = time.time()
    indptr = jax.device_put(jnp.asarray(indptr_np.astype(np.int32)))
    indices = jax.device_put(jnp.asarray(indices_np.astype(np.int32)))
    # sync the ~0.5 GB H2D here: device_put is async, and letting the first
    # timed call absorb it misattributes transfer time as compile time.
    # Dependent value fetches on BOTH arrays — block_until_ready can return
    # early through the tunnel (PERF_NOTES.md)
    int(indptr[-1]), int(indices[-1])
    log(f"devices: {jax.devices()} (graph H2D {time.time()-t0:.1f}s)")

    rng = np.random.default_rng(1)
    # synthetic train split, products-sized: 196,615 distinct nodes drawn
    # without replacement (the real split's degree profile is unknowable
    # without the egress-blocked dataset; uniform-without-replacement is
    # the documented stand-in). The e2e epoch consumes ONE PERMUTATION of
    # this split — 193 distinct batches, each seed exactly once — with the
    # last batch padded back up to 1024 from the split (static shapes;
    # +0.5% duplicate seed-slots, reported below). Probe batches for cap
    # calibration and the SEPS sections come from a DIFFERENT shuffle of
    # the same split, so caps are calibrated OUT-OF-POOL and the epoch's
    # cap_overflow counter proves they hold.
    steps_per_epoch = -(-PRODUCTS_TRAIN_NODES // batch)
    split = rng.choice(n_nodes, PRODUCTS_TRAIN_NODES, replace=False).astype(np.int32)
    perm = rng.permutation(split)
    pad = steps_per_epoch * batch - perm.shape[0]
    epoch_seeds = np.concatenate([perm, rng.choice(split, pad, replace=False)])
    seeds_epoch = jax.device_put(
        jnp.asarray(epoch_seeds.reshape(steps_per_epoch, batch))
    )
    probe = rng.permutation(split)[: 24 * batch].reshape(24, batch)
    seeds_all = jax.device_put(jnp.asarray(probe))

    # 128-lane tile layout (the library's TPU default): row map host-built
    # (cheap numpy work, ~20 MB upload), the 1.45 GB tile table built ON
    # DEVICE by one [M, 128] gather — shipping it through the tunnel would
    # cost ~25-45 s
    from quiver_tpu.ops.sample import (
        build_tiled_device,
        tiled_base_host,
        tiled_rowmap_host,
    )

    t0 = time.time()
    bd_np, m_rows = tiled_base_host(indptr_np)
    row_start, row_width = tiled_rowmap_host(indptr_np)
    bd = jax.device_put(jnp.asarray(bd_np))
    tiles = build_tiled_device(
        indices,
        jax.device_put(jnp.asarray(row_start.astype(np.int32))),
        jax.device_put(jnp.asarray(row_width)),
    )
    int(tiles[-1, -1])
    log(f"tiled layout: {m_rows} x 128 rows built on device in {time.time()-t0:.1f}s")

    context = {}
    context["rpc_floor_s"] = round(measure_rpc_floor(), 3)
    caps = None
    try:
        caps = calibrate_bench_caps(indptr, indices, seeds_all, batch)
    except Exception as exc:
        log(f"cap calibration failed: {exc}")
    results = bench_sampling(context, bd, tiles, seeds_all, caps)
    # products-like feature table, generated ON DEVICE (a host-side table
    # would cost minutes of tunnel transfer); shared by both sections
    dim = 100
    table = jax.jit(
        lambda k: jax.random.normal(k, (n_nodes, dim), jnp.float32)
    )(jax.random.key(7))
    # e2e runs FIRST after the SEPS legs: its two epoch numbers are
    # headline metrics, and a slow-tunnel day (graph H2D alone has hit
    # 100 s) must starve the auxiliary sections, not these
    try:
        if remaining() > 120:
            context["e2e_epoch_distinct_seeds"] = int(PRODUCTS_TRAIN_NODES)
            context["e2e_epoch_pad_seeds"] = int(
                steps_per_epoch * batch - PRODUCTS_TRAIN_NODES
            )
            bench_e2e(context, bd, tiles, seeds_epoch, table, caps=caps)
        else:
            log("budget exhausted before e2e bench")
    except Exception as exc:
        log(f"e2e bench failed: {exc}")
    try:
        if remaining() > 60:
            bench_feature(context, table)
        else:
            log("budget exhausted before feature bench")
    except Exception as exc:
        log(f"feature bench failed: {exc}")
    try:
        if remaining() > 60:
            bench_quant_feature(context, table)
        else:
            log("budget exhausted before quant feature bench")
    except Exception as exc:
        log(f"quant feature bench failed: {exc}")
    try:
        if remaining() > 60:
            bench_host_sampler(
                context, indptr_np, indices_np,
                np.asarray(seeds_all)[:4],
            )
        else:
            log("budget exhausted before host sampler bench")
    except Exception as exc:
        log(f"host sampler bench failed: {exc}")
    try:
        if remaining() > 150:
            bench_tiered_pipeline(context, indptr_np, indices_np, caps)
        else:
            log("budget exhausted before tiered pipeline bench")
    except Exception as exc:
        log(f"tiered pipeline bench failed: {exc}")
    try:
        if remaining() > 120:
            bench_serve(context, indptr_np, indices_np, table, caps)
        else:
            log("budget exhausted before serve bench")
    except Exception as exc:
        log(f"serve bench failed: {exc}")
    try:
        if remaining() > 30:
            bench_tier_rows(context)
        else:
            log("budget exhausted before tier-row bench")
    except Exception as exc:
        log(f"tier-row bench failed: {exc}")
    try:
        if remaining() > 30:
            bench_stream(context)
        else:
            log("budget exhausted before stream bench")
    except Exception as exc:
        log(f"stream bench failed: {exc}")
    try:
        if remaining() > 120:
            bench_workloads(context)
        else:
            log("budget exhausted before workloads bench")
    except Exception as exc:
        log(f"workloads bench failed: {exc}")

    seps_fused = results.get("fused", 0.0)
    print(
        json.dumps(
            {
                "metric": "neighbor_sampling_throughput",
                "value": round(seps_fused, 1),
                "unit": "sampled_edges_per_sec",
                "vs_baseline": round(seps_fused / BASELINE_SEPS, 4),
                "context": context,
            }
        )
    )


if __name__ == "__main__":
    main()

"""Headline benchmark: k-hop neighbor sampling throughput (SEPS).

Mirrors the reference's benchmarks/sample/bench_sampler.py (SEPS = sampled
edges per second, bench_sampler.py:14-16) on an ogbn-products-scale synthetic
graph, fanout [15, 10, 5], batch 1024 — the config behind the reference's
headline 34.29M SEPS UVA number (docs/Introduction_en.md:41, BASELINE.md).

Timing is tunnel-safe: every iteration's edge count folds into a dependent
accumulator and ONE scalar fetch ends the run, so the device must have
finished every sample step before the clock stops (block_until_ready alone
can return early through the remote-TPU relay).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_SEPS = 34.29e6  # reference: 1 GPU, UVA, ogbn-products [15,10,5]


def enable_compile_cache():
    """Persistent XLA compile cache next to the repo: repeat runs of the
    same shapes skip the (remote) compile entirely."""
    import jax

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as exc:  # cache is an optimization, never a requirement
        log(f"compile cache unavailable: {exc}")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_graph(n_nodes=2_449_029, n_edges=61_859_140, seed=0):
    """products-scale random graph (node/edge counts = ogbn-products)."""
    rng = np.random.default_rng(seed)
    log(f"generating graph: {n_nodes} nodes, {n_edges} edges")
    src = rng.integers(0, n_nodes, n_edges, dtype=np.int64)
    dst = rng.integers(0, n_nodes, n_edges, dtype=np.int64)
    order = np.argsort(src, kind="stable")
    src = src[order]
    dst = dst[order]
    indptr = np.zeros(n_nodes + 1, dtype=np.int64)
    np.cumsum(np.bincount(src, minlength=n_nodes), out=indptr[1:])
    return indptr, dst


def measure(run_jit, graph_args, seed_batches, iters, warmup=3):
    """Dependent-accumulation timing: returns (seps, total_edges)."""
    import jax
    import jax.numpy as jnp

    acc = jnp.int32(0)
    for i in range(warmup):
        acc = acc + run_jit(*graph_args, jax.random.key(i), seed_batches[i % len(seed_batches)])
    int(acc)  # sync
    t0 = time.time()
    acc = jnp.int32(0)
    for i in range(iters):
        acc = acc + run_jit(*graph_args, jax.random.key(100 + i), seed_batches[i % len(seed_batches)])
    total_edges = int(acc)  # single dependent fetch == full completion
    dt = time.time() - t0
    return total_edges / dt, total_edges


def main():
    enable_compile_cache()
    import jax
    import jax.numpy as jnp

    from quiver_tpu.pyg.sage_sampler import sample_dense_fused, sample_dense_pure

    batch = 1024
    sizes = (15, 10, 5)
    n_nodes = 2_449_029
    iters = 20

    indptr_np, indices_np = build_graph(n_nodes=n_nodes)
    # graph arrays are jit ARGUMENTS, not closure constants: embedding a
    # 61M-element array as an XLA constant costs ~2 minutes of compile
    indptr = jax.device_put(jnp.asarray(indptr_np.astype(np.int32)))
    indices = jax.device_put(jnp.asarray(indices_np.astype(np.int32)))
    log(f"devices: {jax.devices()}")

    def run_fused(ip, ix, key, seeds):
        ds = sample_dense_fused(ip, ix, key, seeds, sizes)
        return sum(adj.mask.sum(dtype=jnp.int32) for adj in ds.adjs)

    def run_dedup(ip, ix, key, seeds):
        ds = sample_dense_pure(ip, ix, key, seeds, sizes)
        return sum(adj.mask.sum(dtype=jnp.int32) for adj in ds.adjs)

    rng = np.random.default_rng(1)
    seed_batches = [
        jnp.asarray(rng.integers(0, n_nodes, batch, dtype=np.int64).astype(np.int32))
        for _ in range(24)
    ]

    context = {}
    fused_jit = jax.jit(run_fused)
    log("compiling fused pipeline...")
    t0 = time.time()
    e = int(fused_jit(indptr, indices, jax.random.key(0), seed_batches[0]))
    compile_fused = time.time() - t0
    log(f"fused compile+first run: {compile_fused:.1f}s, edges/iter={e}")
    seps_fused, edges_f = measure(fused_jit, (indptr, indices), seed_batches, iters)
    log(f"fused  : {seps_fused/1e6:.2f}M SEPS ({edges_f} edges)")
    context["fused_compile_s"] = round(compile_fused, 1)

    seps_dedup = None
    try:
        dedup_jit = jax.jit(run_dedup)
        log("compiling dedup pipeline...")
        t0 = time.time()
        int(dedup_jit(indptr, indices, jax.random.key(0), seed_batches[0]))
        compile_dedup = time.time() - t0
        log(f"dedup compile+first run: {compile_dedup:.1f}s")
        seps_dedup, _ = measure(dedup_jit, (indptr, indices), seed_batches, max(iters // 2, 5))
        log(f"dedup  : {seps_dedup/1e6:.2f}M SEPS (reference-parity reindex path)")
        context["dedup_compile_s"] = round(compile_dedup, 1)
        context["dedup_seps"] = round(seps_dedup, 1)
        context["dedup_vs_uva_baseline"] = round(seps_dedup / BASELINE_SEPS, 4)
    except Exception as exc:  # secondary diagnostic only
        log(f"dedup path failed: {exc}")

    print(
        json.dumps(
            {
                "metric": "neighbor_sampling_throughput",
                "value": round(seps_fused, 1),
                "unit": "sampled_edges_per_sec",
                "vs_baseline": round(seps_fused / BASELINE_SEPS, 4),
                "context": context,
            }
        )
    )


if __name__ == "__main__":
    main()

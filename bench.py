"""Headline benchmark: k-hop neighbor sampling throughput (SEPS).

Mirrors the reference's benchmarks/sample/bench_sampler.py (SEPS = sampled
edges per second, bench_sampler.py:14-16) on an ogbn-products-scale synthetic
graph, fanout [15, 10, 5], batch 1024 — the config behind the reference's
headline 34.29M SEPS UVA number (docs/Introduction_en.md:41, BASELINE.md).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np

BASELINE_SEPS = 34.29e6  # reference: 1 GPU, UVA, ogbn-products [15,10,5]


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_graph(n_nodes=2_449_029, n_edges=61_859_140, seed=0):
    """products-scale random graph (node/edge counts = ogbn-products)."""
    rng = np.random.default_rng(seed)
    log(f"generating graph: {n_nodes} nodes, {n_edges} edges")
    src = rng.integers(0, n_nodes, n_edges, dtype=np.int64)
    dst = rng.integers(0, n_nodes, n_edges, dtype=np.int64)
    order = np.argsort(src, kind="stable")
    src = src[order]
    dst = dst[order]
    indptr = np.zeros(n_nodes + 1, dtype=np.int64)
    np.cumsum(np.bincount(src, minlength=n_nodes), out=indptr[1:])
    return indptr, dst


def main():
    import jax
    import jax.numpy as jnp

    from quiver_tpu.pyg.sage_sampler import sample_dense_pure

    batch = 1024
    sizes = (15, 10, 5)
    n_nodes = 2_449_029

    indptr_np, indices_np = build_graph(n_nodes=n_nodes)
    indptr = jnp.asarray(indptr_np.astype(np.int32))
    indices = jnp.asarray(indices_np.astype(np.int32))
    log(f"devices: {jax.devices()}")

    def run(key, seeds):
        ds = sample_dense_pure(indptr, indices, key, seeds, sizes)
        edges = sum(adj.mask.sum(dtype=jnp.int32) for adj in ds.adjs)
        return edges

    run_jit = jax.jit(run)

    rng = np.random.default_rng(1)
    seed_batches = [
        jnp.asarray(rng.integers(0, n_nodes, batch, dtype=np.int64).astype(np.int32))
        for _ in range(24)
    ]
    log("compiling...")
    t0 = time.time()
    e = run_jit(jax.random.key(0), seed_batches[0])
    jax.block_until_ready(e)
    log(f"compile+first run: {time.time()-t0:.1f}s, edges/iter={int(e)}")

    # warmup
    for i in range(1, 4):
        jax.block_until_ready(run_jit(jax.random.key(i), seed_batches[i]))

    iters = 20
    t0 = time.time()
    edge_counts = []
    for i in range(iters):
        edge_counts.append(run_jit(jax.random.key(100 + i), seed_batches[i % len(seed_batches)]))
    jax.block_until_ready(edge_counts)
    dt = time.time() - t0
    total_edges = int(np.sum([int(x) for x in edge_counts]))
    seps = total_edges / dt
    log(f"{iters} iters in {dt:.3f}s -> {seps/1e6:.2f}M SEPS")

    print(
        json.dumps(
            {
                "metric": "neighbor_sampling_throughput",
                "value": round(seps, 1),
                "unit": "sampled_edges_per_sec",
                "vs_baseline": round(seps / BASELINE_SEPS, 4),
            }
        )
    )


if __name__ == "__main__":
    main()

"""Drop-in ``import quiver`` alias for quiver_tpu.

Reference training scripts are written against ``import quiver`` /
``from quiver.pyg import GraphSageSampler`` / ``import
quiver.multiprocessing`` (srcs/python/quiver/__init__.py:2-17). This alias
package lets those scripts run against the TPU engine unchanged: the full
quiver_tpu surface is re-exported, and a meta-path finder resolves ANY
``quiver.<path>`` import — at any depth — to the very same module object as
``quiver_tpu.<path>`` (no duplicate module execution, class identity
preserved).
"""

import importlib
import importlib.abc
import importlib.util
import sys

from quiver_tpu import *  # noqa: F401,F403 — the drop-in surface
from quiver_tpu import __all__, __version__  # noqa: F401


class _AliasLoader(importlib.abc.Loader):
    """Hands the import machinery the REAL quiver_tpu module object, so
    ``quiver.x.y`` IS ``quiver_tpu.x.y`` (one module, one execution)."""

    def __init__(self, real_name: str):
        self._real_name = real_name
        self._orig_spec = None
        self._orig_loader = None

    def create_module(self, spec):
        mod = importlib.import_module(self._real_name)
        # the import machinery is about to stamp the alias spec/loader onto
        # this (shared!) module object; remember the real identity so
        # exec_module can restore it — otherwise importlib.reload and
        # __spec__ introspection on quiver_tpu.* break after any quiver.*
        # import, and relative imports warn (__package__ != __spec__.parent)
        self._orig_spec = mod.__spec__
        self._orig_loader = getattr(mod, "__loader__", None)
        return mod

    def exec_module(self, module):  # already executed as quiver_tpu.*
        module.__spec__ = self._orig_spec
        module.__loader__ = self._orig_loader


class _AliasFinder(importlib.abc.MetaPathFinder):
    def find_spec(self, fullname, path=None, target=None):
        if fullname == "quiver" or not fullname.startswith("quiver."):
            return None
        real = "quiver_tpu." + fullname.split(".", 1)[1]
        try:
            if importlib.util.find_spec(real) is None:
                return None
        except (ImportError, ModuleNotFoundError):
            return None
        return importlib.util.spec_from_loader(fullname, _AliasLoader(real))


# FIRST in meta_path: the shared parent modules keep their real __path__,
# so the default PathFinder would otherwise re-load quiver.<pkg>.<mod> from
# the file as a duplicate module (splitting class identity) before this
# finder is ever consulted
sys.meta_path.insert(0, _AliasFinder())

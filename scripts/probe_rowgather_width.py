"""Probe 2: 2-D row-gather rate vs row width, + frontier degree profile.

probe_window_gather showed vmap(dynamic_slice) windows lower to a
catastrophic path at w>=8 (0.9M desc/s). But `row_windows`' [N, 2]
pairing measurably halved degree-lookup cost, i.e. ROW gathers
(jnp.take(table2d, ids, axis=0)) issue near the element-descriptor rate
at small widths. If that holds to w=8..32, an L-aligned edge-block
layout (each node's edges padded to L-lane rows) turns every deg<=L
neighbor fetch into ONE row gather instead of k element gathers.

Measures:
  - row gather [B] rows from [M, L] int32 tables, L in {2,4,8,16,32,64,128}
  - take_along_axis select [B, L] -> [B, K] cost at those widths
  - degree profile of the bench graph: P(deg <= t) unweighted and
    frontier-weighted (size-biased by deg — the fused pipeline's hop
    frontier composition)

Run: python -u scripts/probe_rowgather_width.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def measure_rpc_floor(dev_x, n=6):
    ts = []
    for _ in range(n):
        t0 = time.time()
        float(jnp.sum(dev_x[:8]))
        ts.append(time.time() - t0)
    return float(np.median(ts))


def main():
    from bench import build_graph

    indptr_np, indices_np = build_graph()
    deg = np.diff(indptr_np)
    E = len(indices_np)

    print("== degree profile ==", flush=True)
    w_deg = deg.astype(np.float64) / deg.sum()  # size-biased (frontier) weight
    for t in (2, 4, 5, 8, 10, 15, 16, 32, 64, 128, 256):
        p_plain = float((deg <= t).mean())
        p_front = float(w_deg[deg <= t].sum())
        print(f"deg<={t:4d}: plain {p_plain:6.3f}  frontier-weighted {p_front:6.3f}", flush=True)
    print(f"max deg {deg.max()}, mean {deg.mean():.1f}, median {np.median(deg):.0f}", flush=True)

    B = 180_224
    K = 5
    M = E // 128  # enough rows for any width below

    table_full = jnp.asarray(indices_np[: M * 128].astype(np.int32)).reshape(M, 128)
    table_full.block_until_ready()
    floor = measure_rpc_floor(table_full)
    print(f"rpc floor {floor:.3f}s", flush=True)

    def timed(run, args, iters, label, desc_per_iter, elem_per_iter):
        t0 = time.time()
        out = int(np.asarray(run(*args, jax.random.key(3)))[0])
        compile_s = time.time() - t0
        t0 = time.time()
        out = int(np.asarray(run(*args, jax.random.key(4)))[0])
        dt = max(time.time() - t0 - floor, 1e-9)
        print(
            f"{label:28s}: {dt*1e3/iters:8.2f} ms/iter  "
            f"{desc_per_iter*iters/dt/1e6:8.1f}M rows/s  "
            f"{elem_per_iter*iters/dt/1e6:8.1f}M elem/s  "
            f"(compile+first {compile_s:.1f}s, chk {out & 0xffff})",
            flush=True,
        )

    for L in (2, 4, 8, 16, 32, 64, 128):
        iters = 200 if L <= 32 else 80
        table = table_full[:, :L]

        def make_row(L=L, iters=iters):
            @jax.jit
            def run(tab, key0):
                def body(acc, i):
                    key = jax.random.fold_in(key0, i)
                    rows = jax.random.randint(key, (B,), 0, M, jnp.int32)
                    got = jnp.take(tab, rows, axis=0)
                    return acc + got.sum(dtype=jnp.int32), None

                acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(iters, dtype=jnp.int32))
                return jnp.stack([acc])

            return run

        timed(make_row(), (table,), iters, f"rowgather [B] from [M,{L}]", B, B * L)

    # row gather + in-register lane select to [B, K]
    for L in (8, 16, 32):
        iters = 200
        table = table_full[:, :L]

        def make_rowsel(L=L, iters=iters):
            @jax.jit
            def run(tab, key0):
                def body(acc, i):
                    key = jax.random.fold_in(key0, i)
                    k1, k2 = jax.random.split(key)
                    rows = jax.random.randint(k1, (B,), 0, M, jnp.int32)
                    pos = jax.random.randint(k2, (B, K), 0, L, jnp.int32)
                    got = jnp.take(tab, rows, axis=0)
                    sel = jnp.take_along_axis(got, pos, axis=1)
                    return acc + sel.sum(dtype=jnp.int32), None

                acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(iters, dtype=jnp.int32))
                return jnp.stack([acc])

            return run

        timed(make_rowsel(), (table,), iters, f"rowgather+sel [M,{L}]->{K}", B, B * K)

    # one-hot select alternative (matmul-ish, MXU-friendly) at L=16
    L, iters = 16, 200
    table = table_full[:, :L]

    @jax.jit
    def run_onehot(tab, key0):
        def body(acc, i):
            key = jax.random.fold_in(key0, i)
            k1, k2 = jax.random.split(key)
            rows = jax.random.randint(k1, (B,), 0, M, jnp.int32)
            pos = jax.random.randint(k2, (B, K), 0, L, jnp.int32)
            got = jnp.take(tab, rows, axis=0)  # [B, L]
            oh = (pos[:, :, None] == jnp.arange(L, dtype=jnp.int32)[None, None, :])
            sel = jnp.where(oh, got[:, None, :], 0).sum(axis=2)
            return acc + sel.sum(dtype=jnp.int32), None

        acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(iters, dtype=jnp.int32))
        return jnp.stack([acc])

    timed(run_onehot, (table,), iters, f"rowgather+onehot [M,16]->{K}", B, B * K)


if __name__ == "__main__":
    main()

"""Probe 6: final fetch formulation + honest isolated reindex/FY costs.

probe_tiled_variants: k-split tiled 6.16 ms vs flat-elem-2D 9.40 ms at
(135168, 5) — but rows-1didx (1-D index) hit 7.08, suggesting much of
the win is INDEX SHAPE (1-D vs 2-D), not the tile table. If a 1-D-index
element gather from the FLAT CSR matches, the sampler keeps its layout
and just flattens its index — zero memory cost.

Also re-measures the isolated reindex/FY costs with consumed outputs
(probe_dedup_decomp zeroed its accumulator — DCE'd, the round-4 lesson,
again).

Run: python -u scripts/probe_fetch_final.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

LANE = 128
B = 135_168
K = 5
ITERS = 100


def measure_rpc_floor(dev_x, n=6):
    ts = []
    for _ in range(n):
        t0 = time.time()
        float(jnp.sum(dev_x[:8]))
        ts.append(time.time() - t0)
    return float(np.median(ts))


def main():
    from bench import build_graph
    from quiver_tpu.ops.reindex import local_reindex
    from quiver_tpu.ops.sample import fisher_yates_positions, row_windows

    indptr_np, indices_np = build_graph()
    E = len(indices_np)
    M = E // LANE
    indptr = jnp.asarray(indptr_np)
    indices = jnp.asarray(indices_np.astype(np.int32))
    tiles = indices[: M * LANE].reshape(M, LANE)
    tiles.block_until_ready()
    floor = measure_rpc_floor(tiles)
    print(f"rpc floor {floor:.3f}s", flush=True)

    def timed(run, args, label, iters=ITERS):
        t0 = time.time()
        out = int(np.asarray(run(*args, jax.random.key(5)))[0])
        compile_s = time.time() - t0
        t0 = time.time()
        out = int(np.asarray(run(*args, jax.random.key(6)))[0])
        dt = max(time.time() - t0 - floor, 1e-9)
        print(
            f"{label:30s}: {dt*1e3/iters:7.2f} ms/iter  "
            f"(compile+first {compile_s:.1f}s, chk {out & 0xffff})",
            flush=True,
        )

    def scanned(body_fn, iters=ITERS):
        @jax.jit
        def run(ip, flat_tab, tab, key0):
            def body(acc, i):
                kk = jax.random.fold_in(key0, i)
                return acc + body_fn(ip, flat_tab, tab, kk), None

            acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(iters, dtype=jnp.int32))
            return jnp.stack([acc])

        return run

    A = (indptr, indices, tiles)

    def elem_2d(ip, flat_tab, tab, kk):
        flat = jax.random.randint(kk, (B, K), 0, E, jnp.int32)
        return jnp.take(flat_tab, flat).sum(dtype=jnp.int32)

    def elem_1d(ip, flat_tab, tab, kk):
        flat = jax.random.randint(kk, (B, K), 0, E, jnp.int32)
        got = jnp.take(flat_tab, flat.reshape(-1)).reshape(B, K)
        return got.sum(dtype=jnp.int32)

    def elem_1dT(ip, flat_tab, tab, kk):
        flat = jax.random.randint(kk, (B, K), 0, E, jnp.int32)
        got = jnp.take(flat_tab, flat.T.reshape(-1)).reshape(K, B)
        return got.sum(dtype=jnp.int32)

    def ksplit_tiled(ip, flat_tab, tab, kk):
        k1, k2 = jax.random.split(kk)
        rows = jax.random.randint(k1, (B, K), 0, M, jnp.int32)
        lanes = jax.random.randint(k2, (B, K), 0, LANE, jnp.int32)
        acc = jnp.int32(0)
        for j in range(K):
            win = jnp.take(tab, rows[:, j], axis=0)
            oh = lanes[:, j][:, None] == jnp.arange(LANE, dtype=jnp.int32)[None, :]
            acc = acc + jnp.where(oh, win, 0).sum(dtype=jnp.int32)
        return acc

    timed(scanned(elem_2d), A, "elem 2D-idx (current)")
    timed(scanned(elem_1d), A, "elem 1D-idx flat CSR")
    timed(scanned(elem_1dT), A, "elem 1D-idx transposed")
    timed(scanned(ksplit_tiled), A, "k-split tiled")

    # deg-lookup + FY positions only (no neighbor fetch)
    def fy_only(ip, flat_tab, tab, kk):
        cur = jax.random.randint(kk, (B,), 0, ip.shape[0] - 1, jnp.int32)
        ptr, deg = row_windows(ip, cur)
        pos, valid = fisher_yates_positions(kk, deg, K)
        return (
            pos.sum(dtype=jnp.int32)
            + valid.sum(dtype=jnp.int32)
            + ptr.sum().astype(jnp.int32)
        )

    timed(scanned(fy_only), A, "deg-lookup + FY only")

    # isolated reindex at hop-3 shape, outputs CONSUMED
    S3, k3 = 135_168, 5
    RITERS = 40

    def reindex3(ip, flat_tab, tab, kk):
        seeds = jax.random.randint(kk, (S3,), 0, ip.shape[0] - 1, jnp.int32)
        nbrs = jax.random.randint(
            jax.random.fold_in(kk, 1), (S3, k3), 0, ip.shape[0] - 1, jnp.int32
        )
        res = local_reindex(seeds, jnp.ones((S3,), bool), nbrs, jnp.ones((S3, k3), bool))
        return (
            res.count
            + res.n_id.sum(dtype=jnp.int32)
            + res.local_nbrs.sum(dtype=jnp.int32)
            + res.local_seeds.sum(dtype=jnp.int32)
        )

    timed(scanned(reindex3, RITERS), A, "reindex hop3 (811k) consumed", iters=RITERS)

    S2, k2 = 16_384, 10

    def reindex2(ip, flat_tab, tab, kk):
        seeds = jax.random.randint(kk, (S2,), 0, ip.shape[0] - 1, jnp.int32)
        nbrs = jax.random.randint(
            jax.random.fold_in(kk, 1), (S2, k2), 0, ip.shape[0] - 1, jnp.int32
        )
        res = local_reindex(seeds, jnp.ones((S2,), bool), nbrs, jnp.ones((S2, k2), bool))
        return (
            res.count
            + res.n_id.sum(dtype=jnp.int32)
            + res.local_nbrs.sum(dtype=jnp.int32)
        )

    timed(scanned(reindex2, RITERS), A, "reindex hop2 (180k) consumed", iters=RITERS)


if __name__ == "__main__":
    main()

"""Probe 2: de-noised gather-rate comparison + parallel-take concurrency.

Probe 1 (probe_gather_pack.py) had ~0.1s measured windows -> tunnel RPC
jitter (~0.05-0.3s) dominated. Here ITERS=100 so compute is ~1-2s, and
each config is timed 3x to show spread.

Configs:
  a. plain take, [2.45M, 100] f32       (the hot-gather op as benched)
  b. pack=2 one-hot select, [1.22M,200] (the packing candidate)
  c. G=4 independent takes of W/4 each, concatenated (DMA concurrency?)
  d. plain take, dim=200 f32            (row-rate at 2x width)
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

W = 262_144
ITERS = 100
N0, D = 2_449_029, 100


def timed3(fn, *args):
    float(fn(*args))  # compile + warm
    out = []
    for _ in range(3):
        t0 = time.time()
        float(fn(*args))
        out.append(time.time() - t0)
    return out


def report(name, dts, rows_per_iter=W):
    rates = [ITERS * rows_per_iter / dt / 1e6 for dt in dts]
    print(
        f"  {name:28s}: " + " ".join(f"{r:6.1f}" for r in rates) + " M rows/s"
        f"   (dt {min(dts):.2f}-{max(dts):.2f}s)"
    )
    return max(rates)


def main():
    print("devices:", jax.devices())
    idx = jax.random.randint(jax.random.key(9), (W,), 0, N0, dtype=jnp.int32)

    # a. plain dim-100
    tab = jax.random.normal(jax.random.key(1), (N0, D), jnp.float32)

    @jax.jit
    def plain(tab, idx):
        def body(acc, i):
            ids = (idx + i * 977) % N0
            return acc + jnp.take(tab, ids, axis=0).sum(dtype=jnp.float32), None

        acc, _ = lax.scan(body, jnp.float32(0), jnp.arange(ITERS, dtype=jnp.int32))
        return acc

    jax.block_until_ready((tab, idx))
    report("a plain take dim100", timed3(plain, tab, idx))

    # c. 4 independent takes of W/4, same table (tests DMA queue concurrency)
    @jax.jit
    def par4(tab, idx):
        parts = jnp.split(idx, 4)

        def body(acc, i):
            s = jnp.float32(0)
            for part in parts:
                ids = (part + i * 977) % N0
                s = s + jnp.take(tab, ids, axis=0).sum(dtype=jnp.float32)
            return acc + s, None

        acc, _ = lax.scan(body, jnp.float32(0), jnp.arange(ITERS, dtype=jnp.int32))
        return acc

    report("c 4 parallel takes", timed3(par4, tab, idx))
    del tab

    # b. pack=2 one-hot select
    npk = (N0 + 1) // 2
    tab2 = jax.random.normal(jax.random.key(2), (npk, 2 * D), jnp.float32)

    @jax.jit
    def pack2(tab2, idx):
        def body(acc, i):
            ids = (idx + i * 977) % N0
            packed = jnp.take(tab2, ids // 2, axis=0).reshape(W, 2, D)
            sel = jax.nn.one_hot(ids % 2, 2, dtype=packed.dtype)
            rows = jnp.einsum("wp,wpd->wd", sel, packed)
            return acc + rows.sum(dtype=jnp.float32), None

        acc, _ = lax.scan(body, jnp.float32(0), jnp.arange(ITERS, dtype=jnp.int32))
        return acc

    jax.block_until_ready(tab2)
    report("b pack2 one-hot", timed3(pack2, tab2, idx))

    # d. plain take at dim 200 (raw row rate at 2x width)
    @jax.jit
    def plain200(tab2, idx):
        def body(acc, i):
            ids = (idx + i * 977) % npk
            return acc + jnp.take(tab2, ids, axis=0).sum(dtype=jnp.float32), None

        acc, _ = lax.scan(body, jnp.float32(0), jnp.arange(ITERS, dtype=jnp.int32))
        return acc

    report("d plain take dim200", timed3(plain200, tab2, idx))
    del tab2


if __name__ == "__main__":
    main()

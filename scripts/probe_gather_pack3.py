"""Probe 3: separate true device gather rate from per-dispatch RPC overhead.

Sweep ITERS; fit dt = overhead + iters * t_iter. Also time a trivial
dispatch to measure the RPC floor directly.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

W = 262_144
N0, D = 2_449_029, 100


def main():
    print("devices:", jax.devices())
    tab = jax.random.normal(jax.random.key(1), (N0, D), jnp.float32)
    idx = jax.random.randint(jax.random.key(9), (W,), 0, N0, dtype=jnp.int32)
    jax.block_until_ready((tab, idx))

    # RPC floor: trivial scalar program, 5 reps
    @jax.jit
    def triv(x):
        return x + 1.0

    float(triv(jnp.float32(0)))
    for _ in range(2):
        t0 = time.time()
        float(triv(jnp.float32(1)))
        print(f"  trivial dispatch+fetch: {time.time()-t0:.3f}s")

    def make(iters):
        @jax.jit
        def run(tab, idx):
            def body(acc, i):
                ids = (idx + i * 977) % N0
                return acc + jnp.take(tab, ids, axis=0).sum(dtype=jnp.float32), None

            acc, _ = lax.scan(body, jnp.float32(0), jnp.arange(iters, dtype=jnp.int32))
            return acc

        return run

    pts = []
    for iters in (10, 50, 100, 200, 400, 800):
        run = make(iters)
        float(run(tab, idx))
        best = min(
            (lambda t0: (float(run(tab, idx)), time.time() - t0)[1])(time.time())
            for _ in range(3)
        )
        rate = iters * W / best / 1e6
        pts.append((iters, best))
        print(f"  iters={iters:4d}: dt {best:.3f}s  -> {rate:6.1f}M rows/s apparent")

    # least-squares fit dt = a + b*iters
    xs = np.array([p[0] for p in pts], dtype=np.float64)
    ys = np.array([p[1] for p in pts], dtype=np.float64)
    b, a = np.polyfit(xs, ys, 1)
    print(f"  fit: overhead {a*1e3:.0f} ms + {b*1e3:.3f} ms/iter")
    print(f"  TRUE device rate: {W/b/1e6:.1f}M rows/s = {W/b*D*4/1e9:.1f} GB/s")


if __name__ == "__main__":
    main()

"""Synthetic online-serving probe: QPS / tail latency / cache hit rate vs
request skew, and pipelined-dispatch overlap vs the in-flight window.

Replays seeded Zipfian request traces through the REAL serving engine
(`quiver_tpu.serve.ServeEngine` — micro-batching, coalescing, embedding
cache, bounded in-flight window) over a small community graph, under
SATURATED load (several closed-loop client threads + the engine's poller
threads), at 3 skew settings x max_in_flight 1 / 2 / 4, and prints ONE
json line (written to SERVE_r02.json by the round driver). On this 1-core
CPU box the absolute QPS is a floor, not a ceiling — the point of the
artifact is the TRAJECTORY: how hit rate, coalescing, dispatch count, and
the MEASURED per-stage overlap (`stats.spans.overlap_summary()`, same
machinery as the tiered training pipeline) move with skew and window size.

Also measures the serve dispatch cost SPLIT the analytic model wants:
`inference.sample_batch` vs `inference.forward_logits` timed separately
(the two stages of `batch_logits`), fed to `scaling.serve_table` — the
eval-shaped costs NEXT.md follow-up (b) asked for, replacing the
pessimistic train-step bound.

Usage: JAX_PLATFORMS=cpu python scripts/serve_probe.py [--requests 400]
       [--out SERVE_r02.json]
"""

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def community_graph(n_comm=4, per_comm=120, intra=10, dim=32, seed=0):
    rng = np.random.default_rng(seed)
    n = n_comm * per_comm
    src, dst = [], []
    for u in range(n):
        cu = u // per_comm
        for v in rng.choice(per_comm, intra, replace=False) + cu * per_comm:
            src.append(u)
            dst.append(int(v))
    feat = rng.standard_normal((n, dim)).astype(np.float32)
    return np.stack([np.array(src), np.array(dst)]), feat, n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=2000)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--clients", type=int, default=4)
    # cache off by default: SERVE_r01.json already charts hit-rate vs skew;
    # this round's sweep isolates the DISPATCH path the window pipelines
    ap.add_argument("--cache-entries", type=int, default=0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from quiver_tpu import CSRTopo
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.parallel.scaling import format_serve_markdown, serve_table
    from quiver_tpu.pyg.sage_sampler import GraphSageSampler
    from quiver_tpu.serve import (
        ServeConfig,
        ServeEngine,
        trace_skew_stats,
        zipfian_trace,
    )

    edge_index, feat, n = community_graph()
    # heavy enough that the dispatch stage (XLA forward, GIL released) is a
    # real fraction of a flush — the regime where the in-flight window can
    # actually hide host batching under device execution on this 1-core box
    model = GraphSAGE(hidden_dim=64, out_dim=8, num_layers=2, dropout=0.0)

    def make_sampler():
        return GraphSageSampler(
            CSRTopo(edge_index=edge_index), sizes=[8, 8], mode="TPU", seed=1
        )

    s0 = make_sampler()
    ds0 = s0.sample_dense(np.arange(args.max_batch, dtype=np.int64))
    params = model.init(
        jax.random.key(0), jnp.zeros((ds0.n_id.shape[0], feat.shape[1])), ds0.adjs
    )

    def run(alpha, max_in_flight):
        eng = ServeEngine(
            model, params, make_sampler(), feat,
            ServeConfig(max_batch=args.max_batch, max_delay_ms=2.0,
                        cache_entries=args.cache_entries,
                        max_in_flight=max_in_flight),
        )
        # every bucket's compile out of the timed window (warmup rides a
        # twin sampler: the serving key stream is untouched)
        eng.warmup()
        eng.cache.invalidate()
        eng.reset_stats()
        trace = zipfian_trace(n, args.requests, alpha=alpha, seed=42)
        chunks = np.array_split(trace, args.clients)
        errors = []

        def client(chunk):
            try:
                eng.predict(chunk, timeout=300)
            except Exception as exc:  # surfaced in the artifact, not lost
                errors.append(repr(exc))

        t0 = time.perf_counter()
        with eng:  # max_in_flight poller threads + inline client flushes
            threads = [threading.Thread(target=client, args=(c,)) for c in chunks]
            [t.start() for t in threads]
            [t.join() for t in threads]
        wall = time.perf_counter() - t0
        s = eng.stats
        lat = s.latency.snapshot()
        ov = s.spans.overlap_summary()
        return {
            "alpha": alpha,
            "max_in_flight": max_in_flight,
            "clients": args.clients,
            "cache_entries": args.cache_entries,
            "skew": trace_skew_stats(trace),
            # a timed-out/failed client means NOT all requests were
            # served: recording requests/wall would fake a QPS — null it
            # (and the aggregate below skips the window entirely)
            "qps": round(args.requests / wall, 1) if not errors else None,
            "p50_ms": round(lat["p50_ms"], 3),
            "p95_ms": round(lat["p95_ms"], 3),
            "p99_ms": round(lat["p99_ms"], 3),
            "dispatches": s.dispatches,
            "dispatched_seeds": s.dispatched_seeds,
            "padded_seeds": s.padded_seeds,
            "coalesced": s.coalesced,
            "cache_hit_rate": round(s.cache.hit_rate, 4),
            "inflight_peak": s.inflight_peak,
            "overlap_frac": ov.get("overlap_frac", 0.0),
            "hidden_frac_measured": ov.get("hidden_frac_measured", 0.0),
            "stage_busy_s": ov.get("busy_s", {}),
            "errors": errors,
            "requests_per_dispatch": round(
                args.requests / max(s.dispatches, 1), 2
            ),
        }

    points = []
    for alpha in (0.0, 0.99, 1.3):
        for mif in (1, 2, 4):
            points.append(run(alpha, mif))

    # the acceptance headline: saturated-load throughput per window size,
    # aggregated across the three skews (sum of requests / sum of walls).
    # Per-point QPS at one skew can tie within this 1-core box's noise;
    # the aggregate is the stable comparison. A window with ANY failed
    # point gets no aggregate — a partial trace must not inflate it
    saturated = {}
    for mif in (1, 2, 4):
        ps = [p for p in points if p["max_in_flight"] == mif]
        if any(p["qps"] is None for p in ps):
            saturated[str(mif)] = None
            continue
        wall = sum(args.requests / p["qps"] for p in ps)
        saturated[str(mif)] = round(len(ps) * args.requests / wall, 1)

    # measured per-batch dispatch cost at max_batch, SPLIT the way the
    # engine's stages split it: sample_batch (sampler key draw + k-hop
    # sample) vs forward_logits (gather + jitted apply). The split feeds
    # serve_table the eval-shaped costs directly — no train-step proxy.
    # Shared helper with bench.py's serve section: one methodology.
    from quiver_tpu.inference import _cached_apply, time_eval_split

    apply = _cached_apply(model)
    t_sample, t_forward = time_eval_split(
        apply, params, make_sampler(), feat,
        np.arange(args.max_batch, dtype=np.int64), iters=20,
    )
    pred = serve_table(
        t_sample, 0.0, t_forward, ref_batch=args.max_batch,
        buckets=(args.max_batch,), hit_rates=(0.0, 0.5, 0.9),
        unique_frac=0.8, max_delay_ms=2.0,
    )

    out = {
        "metric": "serve_probe",
        "requests": args.requests,
        "max_batch": args.max_batch,
        "backend": jax.devices()[0].platform,
        "points": points,
        "saturated_qps_by_mif": saturated,
        "measured_sample_s": round(t_sample, 6),
        "measured_forward_s": round(t_forward, 6),
        "measured_dispatch_s": round(t_sample + t_forward, 6),
        "cost_source": "eval_split",  # sample_batch + forward_logits, not a train step
        "serve_table": [p._asdict() for p in pred],
        "serve_table_md": format_serve_markdown(pred),
    }
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")


if __name__ == "__main__":
    main()

"""Synthetic online-serving probe, round 10: cross-host sharded serving —
aggregate QPS / per-shard batch width / exchange bytes vs host count.

Replays seeded Zipfian request traces through the REAL distributed serving
engine (`quiver_tpu.serve.DistServeEngine`: front-end router with
dedup/coalescing + a result cache, seed-ownership split, the serve-shaped
all_to_all exchange, per-owner pipelined `ServeEngine`s over true 1/H
topology + feature shards) on a community graph whose contiguous partition
is k-hop CLOSED — so the shard tables are exactly 1/H with zero halo. Runs
under saturated load (closed-loop client threads + the router's pollers)
at 2 skews x hosts 1 / 2 / 4, and prints ONE json line (written to
SERVE_r03.json by the round driver).

On this 1-core CPU box every "host" shares one core, so absolute QPS does
NOT scale with H here — the hardware-true signal is the TRAJECTORY the
artifact records: per-shard sub-batch width shrinking as 1/H (the term
that divides per-host device time on a real pod), the measured exchange
payload bytes, and BIT-PARITY asserted in-run: every served row is
compared against the offline `batch_logits` replay of the owning shard's
dispatch log through a FULL-graph sampler (`replay_shard_oracle`) — the
acceptance contract that sharding adds nothing numerically.

Also measures the eval-shaped dispatch cost split (`time_eval_split`) and
emits `scaling.serve_table(hosts=H)` for the same host counts — the
analytic aggregate-QPS model (per-shard dispatch + DCN exchange term)
next to the measured trajectory, plus the git revision of the tree that
produced the artifact (SERVE_r01.json is un-rerunnable without digging
through CHANGES.md — never again).

Usage: JAX_PLATFORMS=cpu python scripts/serve_probe.py [--requests 400]
       [--hosts 1,2,4] [--out SERVE_r03.json]
"""

import argparse
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def git_revision() -> str:
    """Best-effort `git rev-parse HEAD` of the repo this probe ran from,
    with a ``-dirty`` suffix when the working tree has uncommitted changes
    (an artifact stamped with a clean-looking revision it wasn't actually
    built from would be worse than no stamp)."""
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=cwd,
            capture_output=True, text=True, timeout=10, check=True,
        ).stdout.strip()
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], cwd=cwd,
            capture_output=True, text=True, timeout=10, check=True,
        ).stdout.strip()
        return rev + ("-dirty" if dirty else "")
    except Exception:
        return "unknown"


def community_graph(n_comm=4, per_comm=120, intra=10, dim=32, seed=0):
    rng = np.random.default_rng(seed)
    n = n_comm * per_comm
    src, dst = [], []
    for u in range(n):
        cu = u // per_comm
        for v in rng.choice(per_comm, intra, replace=False) + cu * per_comm:
            src.append(u)
            dst.append(int(v))
    feat = rng.standard_normal((n, dim)).astype(np.float32)
    return np.stack([np.array(src), np.array(dst)]), feat, n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=400)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--hosts", default="1,2,4")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    hosts_sweep = [int(h) for h in args.hosts.split(",")]

    # the collective serve exchange needs one CPU device per simulated
    # host; must land before jax initializes
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={max(hosts_sweep + [2])}"
    ).strip()

    import jax
    import jax.numpy as jnp

    from quiver_tpu import CSRTopo
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.parallel.scaling import format_serve_markdown, serve_table
    from quiver_tpu.pyg.sage_sampler import GraphSageSampler
    from quiver_tpu.serve import (
        DistServeConfig,
        DistServeEngine,
        ServeConfig,
        replay_shard_oracle,
        trace_skew_stats,
        zipfian_trace,
    )

    edge_index, feat, n = community_graph()
    topo = CSRTopo(edge_index=edge_index)
    SIZES, SEED = [8, 8], 1
    model = GraphSAGE(hidden_dim=64, out_dim=8, num_layers=2, dropout=0.0)

    def make_full_sampler():
        return GraphSageSampler(topo, sizes=SIZES, mode="TPU", seed=SEED)

    s0 = make_full_sampler()
    ds0 = s0.sample_dense(np.arange(args.max_batch, dtype=np.int64))
    params = model.init(
        jax.random.key(0), jnp.zeros((ds0.n_id.shape[0], feat.shape[1])), ds0.adjs
    )

    def run(alpha, hosts):
        # caches ON (router + owners): parity across repeat requests is
        # only well-defined when each node is computed once per version —
        # and a served repeat answered host-side is the production path
        dist = DistServeEngine.build(
            model, params, topo, feat, SIZES, hosts=hosts,
            config=DistServeConfig(
                hosts=hosts, max_batch=args.max_batch, max_delay_ms=2.0,
                record_dispatches=True,
                # a 2-bucket ladder per shard: the full pow2 ladder costs
                # ~6 compiles x shards x ~4 s on this box, and the sweep's
                # signal (width shrink, exchange bytes, parity) doesn't
                # need bucket granularity
                shard_config=ServeConfig(
                    max_batch=args.max_batch,
                    buckets=(8, args.max_batch),
                    max_delay_ms=2.0,
                    record_dispatches=True,
                ),
            ),
            sampler_seed=SEED,
        )
        dist.warmup()
        dist.reset_stats()
        trace = zipfian_trace(n, args.requests, alpha=alpha, seed=42)
        chunks = np.array_split(trace, args.clients)
        results, errors = {}, []

        def client(tid, chunk):
            try:
                results[tid] = (chunk, dist.predict(chunk, timeout=300))
            except Exception as exc:
                errors.append(repr(exc))

        t0 = time.perf_counter()
        with dist:
            threads = [
                threading.Thread(target=client, args=(i, c))
                for i, c in enumerate(chunks)
            ]
            [t.start() for t in threads]
            [t.join() for t in threads]
        wall = time.perf_counter() - t0

        # IN-RUN PARITY: every served row must bit-match the offline
        # replay of the owning shard's dispatch log through a FULL-graph
        # sampler — the probe hard-fails on any mismatch
        parity_rows = 0
        if not errors:
            oracle = replay_shard_oracle(dist, model, params, make_full_sampler, feat)
            for ids, out in results.values():
                for nid, row in zip(ids, out):
                    assert np.array_equal(row, oracle[int(nid)]), (
                        f"PARITY VIOLATION at node {int(nid)} (hosts={hosts})"
                    )
                    parity_rows += 1

        s = dist.stats
        widths = s.mean_sub_batch_width()
        router_mean = s.routed_seeds / max(s.router_dispatches, 1)
        if hosts > 1 and s.router_dispatches:
            # the 1/H width shrink, asserted in-run (uniform-ish ownership
            # split of each flush; slack for small final flushes)
            assert all(w <= router_mean / hosts * 1.6 + 1 for w in widths.values()), (
                widths, router_mean, hosts,
            )
        lat = s.latency.snapshot()
        return {
            "alpha": alpha,
            "hosts": hosts,
            "exchange_mode": dist.exchange_mode,
            "clients": args.clients,
            "skew": trace_skew_stats(trace),
            "qps": round(args.requests / wall, 1) if not errors else None,
            "p50_ms": round(lat["p50_ms"], 3),
            "p99_ms": round(lat["p99_ms"], 3),
            "router_dispatches": s.router_dispatches,
            "routed_seeds": s.routed_seeds,
            "coalesced": s.coalesced,
            "router_cache_hit_rate": round(s.router_cache.hit_rate, 4),
            "mean_router_flush_width": round(router_mean, 2),
            "mean_sub_batch_width": {str(h): round(w, 2) for h, w in widths.items()},
            "exchange_id_bytes": s.exchange_id_bytes,
            "exchange_logit_bytes": s.exchange_logit_bytes,
            "shard_edge_frac": {
                str(h): round(st["edge_frac"], 4)
                for h, st in dist.shard_topo_stats.items()
            },
            "shards_merged": {
                k: v
                for k, v in dist.aggregate_stats()["shards_merged"].items()
                if k in ("dispatches", "dispatched_seeds", "coalesced")
            },
            "parity_rows_checked": parity_rows,
            "errors": errors,
        }

    points = []
    for alpha in (0.0, 1.1):
        for hosts in hosts_sweep:
            points.append(run(alpha, hosts))

    # saturated aggregate per host count (sum of requests / sum of walls
    # across skews); a host count with ANY failed point gets no aggregate
    saturated = {}
    for hosts in hosts_sweep:
        ps = [p for p in points if p["hosts"] == hosts]
        if any(p["qps"] is None for p in ps):
            saturated[str(hosts)] = None
            continue
        wall = sum(args.requests / p["qps"] for p in ps)
        saturated[str(hosts)] = round(len(ps) * args.requests / wall, 1)

    # eval-shaped dispatch cost split at max_batch -> the H-host analytic
    # model (per-shard dispatch + DCN exchange) for the same sweep
    from quiver_tpu.inference import _cached_apply, time_eval_split

    apply = _cached_apply(model)
    t_sample, t_forward = time_eval_split(
        apply, params, make_full_sampler(), feat,
        np.arange(args.max_batch, dtype=np.int64), iters=20,
    )
    tables = {}
    for hosts in hosts_sweep:
        pred = serve_table(
            t_sample, 0.0, t_forward, ref_batch=args.max_batch,
            buckets=(args.max_batch,), hit_rates=(0.0, 0.5, 0.9),
            unique_frac=0.8, max_delay_ms=2.0, hosts=hosts,
            out_dim=model.out_dim,
        )
        tables[str(hosts)] = {
            "rows": [p._asdict() for p in pred],
            "md": format_serve_markdown(pred),
        }

    out = {
        "metric": "serve_probe_dist",
        "git_revision": git_revision(),
        "requests": args.requests,
        "max_batch": args.max_batch,
        "backend": jax.devices()[0].platform,
        "points": points,
        "saturated_qps_by_hosts": saturated,
        "measured_sample_s": round(t_sample, 6),
        "measured_forward_s": round(t_forward, 6),
        "cost_source": "eval_split",
        "serve_table_by_hosts": tables,
    }
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")


if __name__ == "__main__":
    main()

"""Synthetic online-serving probe, round 11: ONE-dispatch serving —
fused AOT-pre-bound bucket executables vs the round-9/10 two-dispatch
path, plus late seed admission under an open-loop Poisson trace.

Replays seeded request traces through the REAL serving stack
(`quiver_tpu.serve.DistServeEngine` router + per-owner `ServeEngine`s) on
a community graph whose contiguous partition is k-hop CLOSED (true 1/H
shards, zero halo). Two serve paths per sweep point:

- **fused** — the round-11 default: ``feature_residency="closure"`` owner
  shards, every owner flush is ONE execute call on a pre-bound
  `inference.BucketPrograms` executable (``execute_calls == dispatches``,
  asserted in-run), late admission on.
- **split** — the round-9/10 baseline: ``feature_residency="exchange"`` +
  ``dispatch_mode="split"`` (sample leg + forward leg per flush,
  ``execute_calls == 2 * dispatches``).

Every sweep point runs ``--repeats`` times and reports MEDIAN + min/max
(NEXT.md: single-run numbers on this noisy 1-core box flip run to run —
one-number points are noise; the spread is part of the artifact). In-run
bit-parity still asserts over all rows: hosts=1 fused output == a plain
single-host `ServeEngine` on the same trace, and every served row (both
paths, both host counts) == the offline `batch_logits` replay of the
owning shard's dispatch log through a FULL-graph sampler
(`replay_shard_oracle`).

The LATE-ADMISSION leg paces submits on a Poisson arrival schedule
against a single-host fused engine driven by a few pump threads at
``max_in_flight=1``: partial age-triggered flushes block on the window
while the device runs the previous flush, and seeds arriving during the
wait ride the blocked flush's pad lanes (``late_admitted > 0`` asserted;
the recovered lanes are bucket slack that rounds 8-10 computed and threw
away). Replay parity asserts after, so admission demonstrably never
perturbs the key stream.

Also measures the dispatch costs three ways — eval-shaped split
(`time_eval_split`), the fused one-program step, and their delta (the
per-flush overhead the 2→1 cut removes) — and emits
`scaling.serve_table(dispatches_per_flush=1 vs 2)` priced with that
measured overhead, next to the measured trajectory. Artifact is stamped
with the producing git revision.

Round 12 adds the OBSERVABILITY legs (ISSUE 7): the same saturated sweep
re-run with the request-lifecycle `trace.EventJournal` + fleet
`MetricsRegistry` enabled — the artifact then carries (a) journal-derived
per-request per-stage p50/p99 (queue vs device vs resolve) and per-flush
pad occupancy, (b) a Perfetto-loadable Chrome-trace timeline
(``--timeline out.json``) whose flush lanes show overlapped in-flight
flushes, (c) the Prometheus text exposition of the fleet registry, and
(d) the measured enabled-vs-disabled saturated-QPS delta
(``serve_obs_overhead_frac``, median-of-3 interleaved runs). Parity is
re-asserted WITH the journal on (observation never feeds control flow).

Round 13 adds the WORKLOAD-SKEW leg (ISSUE 8, ``--skew`` ->
SERVE_r06.json): an alpha in {0.8, 1.1, 1.3} Zipf sweep through engines
with the round-13 frequency sketches on (`trace.WorkloadConfig`),
recording per alpha (a) Space-Saving top-64 vs exact-counter overlap
(>= 90% asserted in-run at alpha 1.3), (b) the sketch's predicted LRU
hit rate at the probe's cache capacity vs the MEASURED `EmbeddingCache`
hit rate under an LRU-faithful sequential drive (within 5 points
asserted at alpha 1.3), (c) per-owner routed load + imbalance +
straggler stats at hosts 1 and 2, and (d) an interleaved median-of-3
sketch-on vs sketch-off saturated-QPS comparison (noise-honest spreads,
same discipline as the journal leg). The alpha-1.3 measured
head-concentration curve feeds `scaling.skew_table` — the predicted
hot-shard replication benefit for ROADMAP item 3, priced from
measurement.

Round 14 adds the DISK-TIER leg (ISSUE 9, ``--tiers`` ->
TIER_r01.json): a dedicated 4800-node community graph whose feature
table is 6.7x the configured host-DRAM budget (disk holding the rest),
served static-placement vs SKETCH-ADAPTED placement (the row-access
sketch + `ServeEngine.adapt_tiers` fenced batches) under an alpha-1.3
Zipf trace whose hotness is PERMUTED off the stored prefix. In-run
asserts: capacity ratio >= 5x, disk-tier gathers bit-equal the in-DRAM
oracle (fp32 exact, int8 codec-exact), and adaptive beats static on
saturated QPS or p99 (median-of-3 interleaved, spreads reported).
Cold-read latency is SIMULATED per row (labeled in the artifact —
this box's page cache makes flat-file reads DRAM-speed) and applied
identically to both placements; measured per-row tier costs price
`scaling.tier_table` rows carried in the artifact.

Round 16 adds the ELASTIC-FLEET leg (ISSUE 11, ``--scale`` ->
SERVE_r08.json): a host-mode hosts=1 fleet ramped 1→2→4→2 under a live
alpha-1.1 Zipf trace via `DistServeEngine.scale` — seed-ownership
ranges migrate one bounded fenced batch at a time (build outside the
fence, per-range flip). In-run asserts: ZERO dropped requests on the
clean ramp, bit-parity of every completed row in every wave against the
epoch-aware `replay_fleet_oracle` (retired engines vouch for their
epochs), and a second ramp with an owner KILLED MID-MIGRATION
(`FaultSpec(at="migration")`) whose in-flight ranges roll
forward/back deterministically, still zero-drop (fallback absorbs),
still parity-true, and bit-identical when replayed. The clean ramp's
measured coverage + routed-flush cost price `scaling.fleet_table`
(add-a-host vs replicate-the-head) in the artifact.

Usage: JAX_PLATFORMS=cpu python scripts/serve_probe.py [--requests 400]
       [--hosts 1,2] [--repeats 3] [--out SERVE_r05.json]
       [--timeline SERVE_r05_timeline.json]
       JAX_PLATFORMS=cpu python scripts/serve_probe.py --skew
       [--skew-requests 3000] [--skew-cache 64] [--out SERVE_r06.json]
       JAX_PLATFORMS=cpu python scripts/serve_probe.py --tiers
       [--tier-requests 600] [--tier-disk-us-per-row 20]
       [--out TIER_r01.json]
       JAX_PLATFORMS=cpu python scripts/serve_probe.py --scale
       [--scale-requests 360] [--migrate-batch 120]
       [--out SERVE_r08.json]
"""

import argparse
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def git_revision() -> str:
    """Best-effort `git rev-parse HEAD` of the repo this probe ran from,
    with a ``-dirty`` suffix when the working tree has uncommitted changes
    (an artifact stamped with a clean-looking revision it wasn't actually
    built from would be worse than no stamp)."""
    cwd = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=cwd,
            capture_output=True, text=True, timeout=10, check=True,
        ).stdout.strip()
        # tracked changes only: the probe itself writes untracked artifacts
        # (--timeline lands before this stamp is taken), and an untracked
        # file does not change what the probe ran
        dirty = subprocess.run(
            ["git", "status", "--porcelain", "--untracked-files=no"],
            cwd=cwd, capture_output=True, text=True, timeout=10, check=True,
        ).stdout.strip()
        return rev + ("-dirty" if dirty else "")
    except Exception:
        return "unknown"


def community_graph(n_comm=4, per_comm=120, intra=10, dim=32, seed=0):
    rng = np.random.default_rng(seed)
    n = n_comm * per_comm
    src, dst = [], []
    for u in range(n):
        cu = u // per_comm
        for v in rng.choice(per_comm, intra, replace=False) + cu * per_comm:
            src.append(u)
            dst.append(int(v))
    feat = rng.standard_normal((n, dim)).astype(np.float32)
    return np.stack([np.array(src), np.array(dst)]), feat, n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=400)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--hosts", default="1,2")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--poisson-requests", type=int, default=300)
    ap.add_argument("--poisson-qps", default="1500,3000")
    ap.add_argument("--timeline", default=None,
                    help="write the Chrome-trace (Perfetto) timeline of "
                         "the instrumented run here")
    ap.add_argument("--journal-events", type=int, default=65536)
    ap.add_argument("--tiers", action="store_true",
                    help="round-14 disk-tier leg: static vs sketch-driven "
                         "adaptive placement -> TIER_r01.json")
    ap.add_argument("--tier-requests", type=int, default=600)
    ap.add_argument("--tier-hbm-rows", type=int, default=480)
    ap.add_argument("--tier-host-rows", type=int, default=720)
    ap.add_argument("--tier-disk-us-per-row", type=float, default=20.0,
                    help="SIMULATED per-row cold-read latency (this box's "
                         "page cache makes flat-file reads DRAM-speed; "
                         "production disk is not; 0 = raw page cache)")
    ap.add_argument("--real-disk", action="store_true",
                    help="round-18 predictive-IO leg (with --tiers): "
                         "page-cache-DEFEATED cold reads (O_DIRECT where "
                         "the filesystem allows, else fadvise DONTNEED "
                         "between legs; method recorded), >=10x-DRAM "
                         "table, mid-run hot-set shift, prefetch-on vs "
                         "prefetch-off vs all-DRAM interleaved "
                         "median-of-3 (-> TIER_r02.json)")
    ap.add_argument("--rd-hbm-rows", type=int, default=240)
    ap.add_argument("--rd-host-rows", type=int, default=360)
    ap.add_argument("--rd-prefetch-rows", type=int, default=2048,
                    help="tier_prefetch_max_rows for the prefetch-on arm "
                         "(closure walk + staging bound — the waste/"
                         "coverage dial; 1024 truncates ~30% of this "
                         "trace's per-burst closure off the staging set)")
    ap.add_argument("--rd-requests", type=int, default=1600,
                    help="real-disk leg trace length (measured window = "
                         "the post-warm two thirds)")
    ap.add_argument("--rd-device-us", type=float, default=250.0,
                    help="RECORDED per-row device-latency model applied "
                         "to every backing read of the measured arms "
                         "(staging reads included — the model can never "
                         "flatter prefetch). This container's backing "
                         "store is hypervisor-cached: even O_DIRECT "
                         "preads land in ~7 us/row, i.e. the guest page "
                         "cache is defeated (evidence recorded) but the "
                         "device itself answers at RAM speed, so a "
                         "latency-hiding claim needs a device latency to "
                         "hide. The sleep is GIL-releasing (IO-shaped: "
                         "pool workers overlap it). 0 disables.")
    ap.add_argument("--stream", action="store_true",
                    help="round-17 streaming-graph leg: serve a Zipf "
                         "trace while appending edges at a fixed rate — "
                         "zero dropped requests, empty-delta bit-parity "
                         "vs the frozen run, closure-touched "
                         "invalidation counts (-> STREAM_r01.json)")
    ap.add_argument("--stream-requests", type=int, default=400)
    ap.add_argument("--stream-edge-every", type=int, default=40,
                    help="requests between edge-arrival events")
    ap.add_argument("--stream-edges-per-event", type=int, default=4)
    ap.add_argument("--stream-stall", action="store_true",
                    help="round-24 zero-stall commit leg: commit storm "
                         "under saturated Zipf traffic, fenced vs "
                         "zero-stall twins — >=10x per-commit stall "
                         "collapse, on-commit p99 <=1.3x frozen-graph, "
                         "run-twice bit-identity, epoch-pinned oracle "
                         "parity (-> STREAM_r02.json)")
    ap.add_argument("--stream-stall-commits", type=int, default=16,
                    help="sequential storm commits per twin")
    ap.add_argument("--stream-stall-requests-per-commit", type=int,
                    default=16)
    ap.add_argument("--stream-stall-edges-per-commit", type=int, default=24)
    ap.add_argument("--stream-stall-traffic-requests", type=int, default=800,
                    help="threaded saturated-traffic requests per twin")
    ap.add_argument("--stream-stall-storm-commits", type=int, default=10,
                    help="commits racing the threaded traffic")
    ap.add_argument("--lifecycle", action="store_true",
                    help="round-21 graph-lifecycle soak: append+expire at "
                         "steady state for ~10^6 edges under live Zipf "
                         "traffic with periodic compaction — flat reserve "
                         "occupancy, zero dropped requests, zero "
                         "StreamCapacityError, in-run temporal oracle "
                         "parity rows (-> LIFECYCLE_r01.json)")
    ap.add_argument("--lifecycle-commits", type=int, default=500)
    ap.add_argument("--lifecycle-edges-per-commit", type=int, default=2000)
    ap.add_argument("--lifecycle-window-commits", type=int, default=8,
                    help="retention window in commit clock ticks — the "
                         "steady-state live set is window*edges_per_commit")
    ap.add_argument("--lifecycle-requests-per-commit", type=int, default=4)
    ap.add_argument("--lifecycle-compact-every", type=int, default=25,
                    help="commits between explicit compaction passes")
    ap.add_argument("--lifecycle-parity-every", type=int, default=100,
                    help="commits between in-run oracle parity checkpoints")
    ap.add_argument("--scale", action="store_true",
                    help="round-16 elastic-fleet leg: ramp a Zipf trace "
                         "1->2->4->2 hosts with live resharding, zero "
                         "dropped requests, epoch-aware oracle parity, "
                         "and an owner kill mid-migration "
                         "(-> SERVE_r08.json)")
    ap.add_argument("--scale-requests", type=int, default=360)
    ap.add_argument("--migrate-batch", type=int, default=120,
                    help="bounded seeds per fenced migration batch")
    ap.add_argument("--faults", action="store_true",
                    help="round-15 fleet-robustness leg: owner-kill "
                         "replay parity, availability/p99 vs hedge "
                         "deadline, replication uplift vs skew_table "
                         "(-> SERVE_r07.json)")
    ap.add_argument("--fault-requests", type=int, default=400)
    ap.add_argument("--hedge-deadlines", default="0,30,120",
                    help="hedge_deadline_ms sweep for the stall leg "
                         "(0 = no deadline)")
    ap.add_argument("--replicate-k", type=int, default=16)
    ap.add_argument("--temporal", action="store_true",
                    help="round-19 workloads leg -> WORKLOAD_r01.json: "
                         "temporal draws vs the host-masked oracle, "
                         "t=inf == frozen weighted engine, streamed-edge "
                         "per-commit visibility, hosts=2 LP pairs "
                         "through the exchange with temporal fleet "
                         "oracle parity, observe-only journal/workload "
                         "parity")
    ap.add_argument("--temporal-requests", type=int, default=320)
    ap.add_argument("--temporal-pairs", type=int, default=120)
    ap.add_argument("--temporal-recency", type=float, default=0.02)
    ap.add_argument("--temporal-quantum", type=float, default=0.05,
                    help="t_quantum in query-time units (the Poisson "
                         "clock runs at --temporal-qps)")
    ap.add_argument("--temporal-qps", type=float, default=2000.0)
    ap.add_argument("--skew", action="store_true",
                    help="run the round-13 workload-skew leg instead of "
                         "the fused/split sweep (-> SERVE_r06.json)")
    ap.add_argument("--skew-requests", type=int, default=3000)
    ap.add_argument("--skew-cache", type=int, default=64)
    ap.add_argument("--skew-alphas", default="0.8,1.1,1.3")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    hosts_sweep = [int(h) for h in args.hosts.split(",")]

    # the collective serve exchange needs one CPU device per simulated
    # host; must land before jax initializes
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={max(hosts_sweep + [2])}"
    ).strip()

    import jax
    import jax.numpy as jnp

    from quiver_tpu import CSRTopo
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.parallel.scaling import format_serve_markdown, serve_table
    from quiver_tpu.pyg.sage_sampler import GraphSageSampler
    from quiver_tpu.serve import (
        DistServeConfig,
        DistServeEngine,
        FaultInjector,
        FaultSpec,
        REPLICA_HOST,
        ServeConfig,
        ServeEngine,
        poisson_arrivals,
        replay_fleet_oracle,
        replay_shard_oracle,
        trace_skew_stats,
        zipfian_trace,
    )
    from quiver_tpu.trace import WorkloadConfig, median_min_max

    edge_index, feat, n = community_graph()
    topo = CSRTopo(edge_index=edge_index)
    SIZES, SEED = [8, 8], 1
    model = GraphSAGE(hidden_dim=64, out_dim=8, num_layers=2, dropout=0.0)

    def make_full_sampler():
        return GraphSageSampler(topo, sizes=SIZES, mode="TPU", seed=SEED)

    s0 = make_full_sampler()
    ds0 = s0.sample_dense(np.arange(args.max_batch, dtype=np.int64))
    params = model.init(
        jax.random.key(0), jnp.zeros((ds0.n_id.shape[0], feat.shape[1])), ds0.adjs
    )

    def build_dist(hosts, path, journal_events=0, workload=None):
        # a 2-bucket ladder per shard keeps compile count down (the sweep's
        # signal doesn't need bucket granularity); fused executables are
        # shared process-wide by shape, so repeats recompile nothing
        shard_cfg = ServeConfig(
            max_batch=args.max_batch,
            buckets=(8, args.max_batch),
            max_delay_ms=2.0,
            record_dispatches=True,
            dispatch_mode="fused" if path == "fused" else "split",
            journal_events=journal_events,
            workload=workload,
        )
        dist = DistServeEngine.build(
            model, params, topo, feat, SIZES, hosts=hosts,
            config=DistServeConfig(
                hosts=hosts, max_batch=args.max_batch, max_delay_ms=2.0,
                record_dispatches=True, shard_config=shard_cfg,
                feature_residency="closure" if path == "fused" else "exchange",
                journal_events=journal_events,
                workload=workload,
            ),
            sampler_seed=SEED,
        )
        dist.warmup()
        dist.reset_stats()
        return dist

    def run_once(alpha, hosts, path, check_parity, journal_events=0,
                 workload=None):
        dist = build_dist(hosts, path, journal_events=journal_events,
                          workload=workload)
        if journal_events or workload is not None:
            # honest overhead accounting: the fleet registry's adapters
            # are installed during the measured run (they are passive
            # readers, but that is the claim being measured)
            dist.fleet_registry()
        trace = zipfian_trace(n, args.requests, alpha=alpha, seed=42)
        chunks = np.array_split(trace, args.clients)
        results, errors = {}, []

        def client(tid, chunk):
            try:
                results[tid] = (chunk, dist.predict(chunk, timeout=300))
            except Exception as exc:
                errors.append(repr(exc))

        t0 = time.perf_counter()
        with dist:
            threads = [
                threading.Thread(target=client, args=(i, c))
                for i, c in enumerate(chunks)
            ]
            [t.start() for t in threads]
            [t.join() for t in threads]
        wall = time.perf_counter() - t0
        if errors:
            raise RuntimeError(f"client errors at {alpha}/{hosts}/{path}: {errors}")

        merged = dist.aggregate_stats()["shards_merged"]
        # the 2->1 dispatch ledger, asserted in-run on every repeat
        if path == "fused":
            assert merged["execute_calls"] == merged["dispatches"], merged
        else:
            assert merged["execute_calls"] == 2 * merged["dispatches"], merged

        parity_rows = 0
        if check_parity:
            # every served row must bit-match the offline replay of the
            # owning shard's dispatch log through a FULL-graph sampler
            oracle = replay_shard_oracle(dist, model, params, make_full_sampler, feat)
            for ids, out in results.values():
                for nid, row in zip(ids, out):
                    assert np.array_equal(row, oracle[int(nid)]), (
                        f"PARITY VIOLATION at node {int(nid)} "
                        f"(hosts={hosts}, path={path})"
                    )
                    parity_rows += 1
        return dist, trace, wall, parity_rows

    # -- round-19 workloads leg (--temporal -> WORKLOAD_r01.json) ------------
    if args.temporal:
        from quiver_tpu.ops.sample import (
            tiled_temporal_sample_layer,
            tiled_weighted_sample_layer,
        )
        from quiver_tpu.serve import lp_trace, temporal_trace
        from quiver_tpu.stream import StreamingTiledGraph
        from quiver_tpu.workloads import (
            TemporalDistServeEngine,
            TemporalServeEngine,
            TemporalTiledGraph,
            host_masked_oracle,
            quantize_t,
            replay_temporal_fleet_oracle,
            replay_temporal_log,
        )

        REC, QUANT = args.temporal_recency, args.temporal_quantum
        rng_t = np.random.default_rng(77)
        E = topo.indices.shape[0]
        base_ts = rng_t.uniform(0.0, 50.0, E).astype(np.float32)
        T0 = 50.0  # queries start after every base edge
        tg = TemporalTiledGraph(topo, base_ts)
        MAXD = 512

        # (a) LAYER PINS, asserted in-run over many draws: host-masked
        # oracle bit-parity + the frozen degeneration (t=inf draws ==
        # the existing weighted sampler over the recency weight tiles)
        bd_d, tiles_d, tt_d = tg.temporal_graph()
        oracle_rows = inf_rows = 0
        for rep in range(4):
            seeds = rng_t.integers(0, n, 64)
            tvals = rng_t.uniform(0.0, 60.0, 64).astype(np.float32)
            key = jax.random.fold_in(jax.random.key(13), rep)
            nb, vl = tiled_temporal_sample_layer(
                bd_d, tiles_d, tt_d, jnp.asarray(seeds),
                jnp.ones((64,), bool), 8, key, jnp.asarray(tvals),
                max_deg=MAXD, recency=REC,
            )
            onb, ovl = host_masked_oracle(
                topo.indptr, topo.indices, base_ts, seeds,
                np.ones(64, bool), 8, key, tvals, max_deg=MAXD,
                recency=REC,
            )
            assert np.array_equal(np.asarray(vl), ovl), "ORACLE VALID MISMATCH"
            assert np.array_equal(
                np.asarray(nb)[np.asarray(vl)], onb[ovl]
            ), "ORACLE DRAW MISMATCH"
            oracle_rows += int(np.asarray(vl).sum())
            wnb, wvl = tiled_weighted_sample_layer(
                bd_d, tiles_d, tg.recency_wtiles(REC), jnp.asarray(seeds),
                jnp.ones((64,), bool), 8, key, max_deg=MAXD,
            )
            inb, ivl = tiled_temporal_sample_layer(
                bd_d, tiles_d, tt_d, jnp.asarray(seeds),
                jnp.ones((64,), bool), 8, key,
                jnp.full((64,), np.inf, jnp.float32), max_deg=MAXD,
                recency=REC,
            )
            assert np.array_equal(np.asarray(ivl), np.asarray(wvl))
            assert np.array_equal(
                np.asarray(inb)[np.asarray(ivl)],
                np.asarray(wnb)[np.asarray(wvl)],
            ), "T=INF != WEIGHTED DRAW"
            inf_rows += int(np.asarray(ivl).sum())

        # (b) ENGINE t=inf pin: a temporal engine (recency 0) queried at
        # t=inf serves BIT-IDENTICAL logits + dispatch composition to
        # the existing FROZEN weighted engine over unit weights — the
        # frozen-graph run IS temporal-at-t=inf, at the serving grain
        topo_w = CSRTopo(edge_index=edge_index,
                         edge_weights=np.ones(edge_index.shape[1],
                                              np.float32))
        sw = GraphSageSampler(topo_w, sizes=SIZES, mode="TPU", seed=SEED,
                              dedup=False, weighted=True, max_deg=MAXD)
        eng_w = ServeEngine(
            model, params, sw, feat,
            ServeConfig(max_batch=args.max_batch,
                        buckets=(8, args.max_batch), max_delay_ms=1e9,
                        record_dispatches=True),
        )
        eng_w.warmup()
        st0 = GraphSageSampler(topo, sizes=SIZES, mode="TPU", seed=SEED,
                               dedup=False, max_deg=MAXD)
        st0.bind_temporal(TemporalTiledGraph(topo, base_ts), recency=0.0)
        eng_t0 = TemporalServeEngine(
            model, params, st0, feat,
            ServeConfig(max_batch=args.max_batch,
                        buckets=(8, args.max_batch), max_delay_ms=1e9,
                        record_dispatches=True),
            t_quantum=0.0,
        )
        eng_t0.warmup()
        tr_inf = zipfian_trace(n, 160, alpha=1.1, seed=21)
        rows_w = eng_w.predict(tr_inf, timeout=120)
        rows_t = eng_t0.predict(tr_inf, t=np.inf, timeout=120)
        assert np.array_equal(rows_w, rows_t), "T=INF ENGINE PARITY VIOLATION"
        assert len(eng_w.dispatch_log) == len(eng_t0.dispatch_log)
        for (pw, nw), (pt, nt, _tv) in zip(eng_w.dispatch_log,
                                           eng_t0.dispatch_log):
            assert nw == nt and np.array_equal(pw, pt)
        inf_engine_rows = len(tr_inf)

        # (c) OBSERVE-ONLY pin: journal + workload telemetry on changes
        # no served bit (same trace, instrumented twin)
        tt_trace = temporal_trace(
            n, args.temporal_requests, alpha=1.1, seed=33,
            qps=args.temporal_qps, t0=T0, edge_every=40,
            edges_per_event=4,
        )

        def run_frozen(journal_events=0, workload=None):
            s = GraphSageSampler(topo, sizes=SIZES, mode="TPU", seed=SEED,
                                 dedup=False, max_deg=MAXD)
            s.bind_temporal(TemporalTiledGraph(topo, base_ts), recency=REC)
            e = TemporalServeEngine(
                model, params, s, feat,
                ServeConfig(max_batch=args.max_batch,
                            buckets=(8, args.max_batch), max_delay_ms=1e9,
                            record_dispatches=True,
                            journal_events=journal_events,
                            workload=workload),
                t_quantum=QUANT,
            )
            e.warmup()
            rows = [
                e.predict([ev[2]], t=ev[3])[0]
                for ev in tt_trace.events() if ev[0] == "request"
            ]
            return e, rows

        eng_plain, rows_plain = run_frozen()
        eng_obs, rows_obs = run_frozen(
            journal_events=args.journal_events,
            workload=WorkloadConfig(topk=64),
        )
        assert all(np.array_equal(a, b)
                   for a, b in zip(rows_plain, rows_obs)), \
            "OBSERVE-ONLY VIOLATION (journal/workload changed bits)"
        assert len(eng_plain.dispatch_log) == len(eng_obs.dispatch_log)
        for (pa, na, ta), (pb, nb_, tb) in zip(eng_plain.dispatch_log,
                                               eng_obs.dispatch_log):
            assert na == nb_ and np.array_equal(pa, pb) \
                and np.array_equal(ta, tb)

        # single-host temporal replay parity against the twin oracle
        def mk_temporal_full():
            s = GraphSageSampler(topo, sizes=SIZES, mode="TPU", seed=SEED,
                                 dedup=False, max_deg=MAXD)
            return s.bind_temporal(TemporalTiledGraph(topo, base_ts),
                                   recency=REC)

        oracle_f = replay_temporal_log(
            eng_plain.dispatch_log, model, params, mk_temporal_full(), feat
        )
        req_list = [ev for ev in tt_trace.events() if ev[0] == "request"]
        replay_rows = 0
        for (_, _, node, tq), row in zip(req_list, rows_plain):
            k = (int(node), float(np.float32(quantize_t(tq, QUANT))))
            assert any(np.array_equal(row, c)
                       for c in oracle_f.get(k, [])), \
                f"TEMPORAL REPLAY VIOLATION at {k}"
            replay_rows += 1

        # (d) STREAMING leg: frozen == empty-delta commits, then LIVE
        # timestamped appends with per-commit visibility at ts +/- eps
        def make_stream_engine(reserve=0.5):
            stream = StreamingTiledGraph(topo, reserve_frac=reserve,
                                         edge_ts=base_ts)
            s = GraphSageSampler(topo, sizes=SIZES, mode="TPU", seed=SEED,
                                 dedup=False, max_deg=MAXD)
            s.bind_temporal(stream, recency=REC)
            e = TemporalServeEngine(
                model, params, s, feat,
                ServeConfig(max_batch=args.max_batch,
                            buckets=(8, args.max_batch), max_delay_ms=1e9,
                            record_dispatches=True),
                t_quantum=QUANT,
            )
            e.warmup()
            return e, stream

        from quiver_tpu.stream import GraphDelta

        eng_es, _ = make_stream_engine()
        rows_es = []
        for ev in tt_trace.events():
            if ev[0] == "edges":
                s = eng_es.update_graph(GraphDelta())
                assert s["edges"] == 0 and eng_es.graph_version == 0
            else:
                rows_es.append(eng_es.predict([ev[2]], t=ev[3])[0])
        assert all(np.array_equal(a, b)
                   for a, b in zip(rows_plain, rows_es)), \
            "EMPTY-DELTA TEMPORAL PARITY VIOLATION"
        empty_delta_rows = len(rows_es)

        eng_live, stream_live = make_stream_engine()
        commits = []
        visibility_checked = dropped = 0
        t_wall0 = time.perf_counter()
        for ev in tt_trace.events():
            if ev[0] == "edges":
                eng_live.stage_edges(ev[1], ev[2], ts=ev[3])
                s = eng_live.update_graph()
                commits.append({
                    "edges": s["edges"],
                    "pad_writes": s["pad_writes"],
                    "tile_spills": s["tile_spills"],
                    "cache_invalidated": s["cache_invalidated"],
                })
                # the acceptance pin: the appended edge is INVISIBLE to
                # a query at ts - eps and VISIBLE at ts + eps (copy-all
                # draw at fanout >= current degree must include it)
                u, v = int(ev[1][0]), int(ev[2][0])
                ets = float(ev[3][0])
                deg_u = stream_live.degree(u)
                g = stream_live.temporal_graph()
                for tq, want in ((ets - 1e-3, False), (ets + 1e-3, True)):
                    nb, vl = tiled_temporal_sample_layer(
                        g[0], g[1], g[2], jnp.asarray([u]),
                        jnp.ones((1,), bool), deg_u,
                        jax.random.key(9), jnp.asarray([tq], jnp.float32),
                        max_deg=MAXD, recency=REC,
                    )
                    drawn = set(
                        np.asarray(nb)[0][np.asarray(vl)[0]].tolist()
                    )
                    # v may pre-exist as an OLDER edge of u; only assert
                    # the new arrival's effect when it is the only (u,v)
                    if want:
                        assert v in drawn, "VISIBILITY: edge not drawable"
                    elif v in drawn:
                        older = [
                            w for w, et in zip(
                                stream_live.neighbors(u),
                                stream_live.adj.neighbors_ts(u),
                            ) if w == v and et <= tq
                        ]
                        assert older, "VISIBILITY: future edge drawn"
                visibility_checked += 2
            else:
                try:
                    eng_live.predict([ev[2]], t=ev[3])
                except Exception:
                    dropped += 1
        wall_live = time.perf_counter() - t_wall0
        assert dropped == 0, f"{dropped} dropped temporal requests"
        assert sum(c["cache_invalidated"] for c in commits) > 0

        # (e) hosts=2 LP leg: split-owner pairs THROUGH the exchange
        # (collective mode ships ids + bitcast query times), every
        # completed endpoint row bit-matching the temporal fleet oracle,
        # and the pair scores a pure function of those rows
        dist = TemporalDistServeEngine.build(
            model, params, topo, base_ts, feat, SIZES, hosts=2,
            config=DistServeConfig(
                hosts=2, max_batch=args.max_batch, max_delay_ms=1e9,
                exchange="collective", record_dispatches=True,
                shard_config=ServeConfig(
                    max_batch=args.max_batch,
                    buckets=(8, args.max_batch), max_delay_ms=1e9,
                    record_dispatches=True,
                ),
            ),
            sampler_seed=SEED, recency=REC, max_deg=MAXD,
            t_quantum=QUANT,
        )
        dist.warmup()
        lp = lp_trace(topo, args.temporal_pairs, alpha=1.1, seed=55,
                      qps=args.temporal_qps, t0=T0)
        owners = dist.global2host
        split_owner_pairs = int(
            (owners[lp.u] != owners[lp.v]).sum()
        )
        assert split_owner_pairs > 0, "trace has no split-owner pairs"
        handles = [
            dist.submit_pair(int(lp.u[i]), int(lp.v[i]),
                             t=float(lp.t_query[i]))
            for i in range(len(lp.u))
        ]
        while any(not h.done() for h in handles) and dist._drainable():
            dist.flush()
        scores = np.asarray([h.result(120) for h in handles], np.float32)
        oracle_d = replay_temporal_fleet_oracle(
            dist, model, params, mk_temporal_full, feat
        )
        lp_parity_rows = 0
        for i, h in enumerate(handles):
            hu, hv = h.rows()
            for node, row in ((int(lp.u[i]), hu), (int(lp.v[i]), hv)):
                k = (node, float(np.float32(
                    quantize_t(float(lp.t_query[i]), QUANT)
                )))
                assert any(np.array_equal(row, c)
                           for c in oracle_d.get(k, [])), \
                    f"LP FLEET PARITY VIOLATION at {k}"
                lp_parity_rows += 1
            re_score = dist.pair_head.score(hu[None], hv[None])[0]
            assert np.float32(re_score) == scores[i]
        pos_scores = scores[lp.label == 1]
        neg_scores = scores[lp.label == 0]

        out = {
            "metric": "serve_probe_temporal",
            "git_revision": git_revision(),
            "backend": jax.devices()[0].platform,
            "config": {
                "requests": args.temporal_requests,
                "pairs": args.temporal_pairs, "alpha": 1.1,
                "recency": REC, "t_quantum": QUANT,
                "qps_clock": args.temporal_qps, "max_batch": args.max_batch,
                "sizes": SIZES, "nodes": n, "max_deg": MAXD,
            },
            "note": (
                "sequential deterministic drive (walls are 1-core "
                "loopback, read the structure); every parity claim is "
                "asserted in-run — a written artifact means they held: "
                "host-masked oracle bit-parity, t=inf == frozen weighted "
                "engine (draws AND served logits), observe-only "
                "journal/workload, frozen == empty-delta commits, "
                "per-commit ts+/-eps visibility, hosts=2 LP endpoint "
                "rows == temporal fleet oracle"
            ),
            "layer_oracle_parity_draws": oracle_rows,
            "layer_t_inf_weighted_parity_draws": inf_rows,
            "engine_t_inf_parity_rows": inf_engine_rows,
            "observe_only_parity_rows": len(rows_plain),
            "single_host_replay_parity_rows": replay_rows,
            "empty_delta_parity_rows": empty_delta_rows,
            "streaming_live": {
                "dropped_requests": dropped,
                "commits": len(commits),
                "delta_edges": eng_live.stats.delta_edges,
                "tile_writes": eng_live.stats.delta_tile_writes,
                "tile_spills": eng_live.stats.delta_tile_spills,
                "cache_invalidated": (
                    eng_live.stats.delta_cache_invalidated
                ),
                "visibility_checks": visibility_checked,
                "reserve_report": stream_live.reserve_report(),
                "qps": round(args.temporal_requests / wall_live, 1),
            },
            "lp_hosts2": {
                "pairs": int(len(lp.u)),
                "split_owner_pairs": split_owner_pairs,
                "endpoint_parity_rows": lp_parity_rows,
                "exchange_id_bytes": dist.stats.exchange_id_bytes,
                "exchange_logit_bytes": dist.stats.exchange_logit_bytes,
                "coalesced": dist.stats.coalesced,
                "router_cache_hits": dist.stats.router_cache.hits,
                "mean_pos_score": float(pos_scores.mean())
                if pos_scores.size else None,
                "mean_neg_score": float(neg_scores.mean())
                if neg_scores.size else None,
            },
        }
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        return

    # -- round-17 streaming-graph leg (--stream -> STREAM_r01.json) ----------
    if args.stream:
        from quiver_tpu.ops.sample import tiled_sample_layer
        from quiver_tpu.serve import delta_interleaved_trace
        from quiver_tpu.stream import GraphDelta, StreamingTiledGraph

        dt = delta_interleaved_trace(
            n, args.stream_requests, alpha=1.1, seed=31,
            edge_every=args.stream_edge_every,
            edges_per_event=args.stream_edges_per_event,
        )
        # cross-community arrivals: half the destinations re-drawn into
        # a DIFFERENT community than their source, so commits exercise
        # real closure extension, not just pad-lane appends
        rng_x = np.random.default_rng(32)
        per_comm = n // 4
        for i in range(dt.n_events):
            for j in range(0, args.stream_edges_per_event, 2):
                cu = int(dt.edge_src[i, j]) // per_comm
                cv = (cu + 1 + rng_x.integers(0, 3)) % 4
                dt.edge_dst[i, j] = cv * per_comm + rng_x.integers(
                    0, per_comm
                )

        def make_single(stream=None):
            smp = GraphSageSampler(topo, sizes=SIZES, mode="TPU",
                                   seed=SEED)
            if stream is not None:
                smp.bind_stream(stream)
            return ServeEngine(
                model, params, smp, feat,
                ServeConfig(max_batch=args.max_batch,
                            max_delay_ms=1e9,
                            record_dispatches=True),
            )

        # (a) PARITY LEG: frozen-graph run vs streaming run committing an
        # EMPTY delta at every event position — bit-identical logits and
        # dispatch logs, asserted in-run
        eng_f = make_single()
        eng_f.warmup()
        rows_f = [eng_f.predict([node])[0]
                  for _, _, node in
                  (e for e in dt.events() if e[0] == "request")]
        stream_e = StreamingTiledGraph(topo, reserve_frac=0.5)
        eng_e = make_single(stream_e)
        eng_e.warmup()
        rows_e = []
        for ev in dt.events():
            if ev[0] == "edges":
                s = eng_e.update_graph(GraphDelta())
                assert s["edges"] == 0 and eng_e.graph_version == 0
            else:
                rows_e.append(eng_e.predict([ev[2]])[0])
        assert all(np.array_equal(a, b) for a, b in zip(rows_f, rows_e)), \
            "EMPTY-DELTA PARITY VIOLATION"
        assert len(eng_f.dispatch_log) == len(eng_e.dispatch_log)
        for (pa, na), (pb, nb) in zip(eng_f.dispatch_log,
                                      eng_e.dispatch_log):
            assert na == nb and np.array_equal(pa, pb)
        parity_rows = len(rows_f)

        # (b) LIVE single-host stream: commit real deltas at the event
        # positions, count closure-touched invalidations, assert
        # per-commit visibility (copy-all draw of the appended source
        # must include the new destination), zero dropped requests
        stream_l = StreamingTiledGraph(topo, reserve_frac=0.5)
        eng_l = make_single(stream_l)
        eng_l.warmup()
        commits = []
        dropped = visibility_checked = 0
        t0 = time.perf_counter()
        for ev in dt.events():
            if ev[0] == "edges":
                d = GraphDelta()
                d.add_edges(ev[1], ev[2])
                s = eng_l.update_graph(d)
                commits.append({
                    "edges": s["edges"],
                    "pad_writes": s["pad_writes"],
                    "tile_spills": s["tile_spills"],
                    "affected_seeds": s["affected_seeds"],
                    "cache_invalidated": s["cache_invalidated"],
                })
                u, v = int(ev[1][0]), int(ev[2][0])
                k = stream_l.degree(u)
                bd_d, tiles_d = stream_l.graph()
                nb, vl = tiled_sample_layer(
                    bd_d, tiles_d, jnp.asarray([u]),
                    jnp.ones((1,), bool), k, jax.random.key(7),
                )
                assert v in set(
                    np.asarray(nb)[0][np.asarray(vl)[0]].tolist()
                ), "VISIBILITY VIOLATION: appended edge not drawable"
                visibility_checked += 1
            else:
                try:
                    eng_l.predict([ev[2]])
                except Exception:
                    dropped += 1
        wall_live = time.perf_counter() - t0
        assert dropped == 0, f"{dropped} dropped requests under streaming"
        assert sum(c["cache_invalidated"] for c in commits) > 0

        # (c) STREAMING FLEET at hosts=2 with replication: same schedule
        # through the routed engine; every completed row must bit-match
        # a pre- or post-delta full-graph oracle candidate
        # reserve 1.0x the built size: cross-community arrivals pull
        # whole communities into an owner's closure, so the fleet plans
        # for up to a full doubling (capacity planning IS the contract —
        # exhaustion is a loud StreamCapacityError, never silent growth)
        cfg2 = DistServeConfig(
            hosts=2, max_batch=args.max_batch, max_delay_ms=1e9,
            exchange="host", record_dispatches=True, streaming=True,
            stream_reserve_frac=1.0,
            replicate_top_k=16, workload=WorkloadConfig(topk=64),
        )
        dist = DistServeEngine.build(
            model, params, topo, feat, SIZES, hosts=2, config=cfg2,
            sampler_seed=SEED,
        )
        dist.warmup()
        rows_d, nodes_d = [], []
        dropped_d = 0
        refreshed = False
        topo_versions = [topo]  # every graph version the fleet served
        t0 = time.perf_counter()
        for ev in dt.events():
            if ev[0] == "edges":
                dist.stage_edges(ev[1], ev[2])
                s = dist.update_graph()
                topo_versions.append(dist._stream_adj.to_csr_topo())
                if not refreshed and dist.workload.hot_set(16).size >= 8:
                    # replicate the live head once telemetry has one
                    dist.refresh_replicas(k=16)
                    refreshed = True
            else:
                h = dist.submit(ev[2])
                while dist._drainable():
                    dist.flush()
                try:
                    rows_d.append(h.result(60))
                    nodes_d.append(ev[2])
                except Exception:
                    dropped_d += 1
        wall_dist = time.perf_counter() - t0
        assert dropped_d == 0, f"{dropped_d} dropped routed requests"
        # parity across graph VERSIONS: a row served between commits v
        # and v+1 was computed on graph version v — it must bit-match a
        # candidate from the fleet replay over SOME version the fleet
        # actually served (the per-version replay is exhaustive because
        # every version's topology was snapshotted at its commit)
        oracles = []
        for tv in topo_versions:
            def mk(tv=tv):
                return GraphSageSampler(tv, sizes=SIZES, mode="TPU",
                                        seed=SEED)
            oracles.append(replay_fleet_oracle(dist, model, params, mk,
                                               feat))
        parity_dist = 0
        for node, row in zip(nodes_d, rows_d):
            cands = [c for o in oracles for c in o.get(int(node), [])]
            assert any(np.array_equal(row, c) for c in cands), \
                f"STREAM-PARITY VIOLATION at node {int(node)}"
            parity_dist += 1

        out = {
            "metric": "serve_probe_stream",
            "git_revision": git_revision(),
            "backend": jax.devices()[0].platform,
            "config": {
                "requests": args.stream_requests, "alpha": 1.1,
                "edge_every": args.stream_edge_every,
                "edges_per_event": args.stream_edges_per_event,
                "max_batch": args.max_batch, "sizes": SIZES,
                "nodes": n, "stream_reserve_frac": 0.5,
            },
            "note": (
                "sequential deterministic drive (QPS numbers are 1-core "
                "loopback walls, read the structure not the absolute); "
                "empty-delta parity, per-commit visibility, zero-drop "
                "and fleet oracle parity are asserted in-run — a "
                "written artifact means they held"
            ),
            "empty_delta_parity_rows": parity_rows,
            "single_host_live": {
                "dropped_requests": dropped,
                "commits": commits,
                "graph_version": eng_l.graph_version,
                "delta_edges": eng_l.stats.delta_edges,
                "tile_writes": eng_l.stats.delta_tile_writes,
                "tile_spills": eng_l.stats.delta_tile_spills,
                "cache_invalidated": eng_l.stats.delta_cache_invalidated,
                "visibility_checks": visibility_checked,
                "free_tile_rows_left": stream_l.free_rows,
                "qps": round(args.stream_requests / wall_live, 1),
            },
            "fleet_hosts2": {
                "dropped_requests": dropped_d,
                "parity_rows_checked": parity_dist,
                "graph_version": dist.graph_version,
                "delta_edges": dist.stats.delta_edges,
                "closure_installs": dist.stats.delta_closure_installs,
                "router_cache_invalidated": (
                    dist.stats.delta_cache_invalidated
                ),
                "replica_delta_invalidations": (
                    dist.stats.replica_delta_invalidations
                ),
                "replica_version": dist.replica_version,
                "qps": round(args.stream_requests / wall_dist, 1),
            },
        }
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        return

    # -- round-24 zero-stall commit leg (--stream-stall -> STREAM_r02.json) --
    if args.stream_stall:
        COMMITS = args.stream_stall_commits
        RPC = args.stream_stall_requests_per_commit
        EPC = args.stream_stall_edges_per_commit
        rng_s = np.random.default_rng(29)
        req_nodes = zipfian_trace(n, COMMITS * RPC, alpha=1.1, seed=41)
        edge_src = zipfian_trace(n, COMMITS * EPC, alpha=1.1, seed=43)
        edge_dst = rng_s.integers(0, n, COMMITS * EPC)

        def build_storm(fenced):
            # reserve 1.0x the built size: Zipf-src arrivals with random
            # destinations pull foreign communities into owner closures
            # (same capacity-planning contract as the --stream leg)
            cfg = DistServeConfig(
                hosts=2, max_batch=args.max_batch, max_delay_ms=1e9,
                exchange="host", record_dispatches=True, streaming=True,
                stream_reserve_frac=1.0, fenced_commits=fenced,
            )
            d = DistServeEngine.build(
                model, params, topo, feat, SIZES, hosts=2, config=cfg,
                sampler_seed=SEED,
            )
            d.warmup()
            return d

        def run_storm(fenced):
            """Deterministic sequential commit storm: a block of Zipf
            requests drained to completion, then a delta commit, COMMITS
            times over. Every served row's epoch is the graph version
            current at its flush (recorded here and — the round-24 pin —
            stamped on the dispatch log rows by the engines themselves)."""
            d = build_storm(fenced)
            rows, vers, stalls = [], [], []
            topo_vs = [topo]  # version v's full-graph topology snapshot
            dropped = 0
            for k in range(COMMITS):
                nodes_k = req_nodes[k * RPC:(k + 1) * RPC]
                hs = [d.submit(int(x)) for x in nodes_k]
                while any(not h.done() for h in hs) and d._drainable():
                    d.flush()
                for h in hs:
                    try:
                        rows.append(np.asarray(h.result(60)))
                        vers.append(d.graph_version)
                    except Exception:
                        dropped += 1
                lo = k * EPC
                d.stage_edges(edge_src[lo:lo + EPC], edge_dst[lo:lo + EPC])
                s = d.update_graph()
                stalls.append(float(s["commit_stall_us"]))
                topo_vs.append(d._stream_adj.to_csr_topo())
            return d, rows, vers, stalls, topo_vs, dropped

        def log_entries(d):
            """Flatten every array the run's dispatch state is made of —
            router log (padded seeds + owner splits), per-host shard logs,
            and all the epoch stamps — for byte-for-byte comparison."""
            out = [np.asarray(d.dispatch_graph_versions, np.int64)]
            for padded, splits in d.dispatch_log:
                out.append(np.asarray(padded))
                for hid, part in splits:
                    out.append(np.asarray([hid]))
                    out.append(np.asarray(part))
            for h in sorted(d.engines):
                eng = d.engines[h]
                out.append(np.asarray(eng.dispatch_graph_versions, np.int64))
                for padded, nvalid in eng.dispatch_log:
                    out.append(np.asarray(padded))
                    out.append(np.asarray([nvalid]))
            return out

        d_zs, rows_zs, vers_zs, stalls_zs, topo_vs, drop_zs = run_storm(False)
        d_f, rows_f, _, stalls_f, _, drop_f = run_storm(True)
        assert drop_zs == 0 and drop_f == 0, "dropped requests in storm"

        # fenced twin parity: the sequential drive admits no races, so the
        # round-23 drain discipline and the zero-stall flip must serve
        # bit-identical logits over identical dispatch state
        assert len(rows_zs) == len(rows_f)
        for a, b in zip(rows_zs, rows_f):
            assert np.array_equal(a, b), "FENCED/ZERO-STALL TWIN DIVERGENCE"
        ents_zs, ents_f = log_entries(d_zs), log_entries(d_f)
        assert len(ents_zs) == len(ents_f)
        for a, b in zip(ents_zs, ents_f):
            assert np.array_equal(a, b), "TWIN DISPATCH-STATE DIVERGENCE"

        # >=10x per-commit stall collapse: the fenced twin's stall is the
        # whole drain+apply hold, the zero-stall twin's is the flip only
        mean_f, mean_zs = float(np.mean(stalls_f)), float(np.mean(stalls_zs))
        assert mean_zs > 0.0
        stall_ratio = mean_f / mean_zs
        assert stall_ratio >= 10.0, (
            f"STALL REDUCTION {stall_ratio:.1f}x < 10x "
            f"(fenced {mean_f:.0f}us, zero-stall {mean_zs:.0f}us)"
        )

        # 100% epoch-aware oracle parity: every served row bit-matches a
        # candidate from the replay of ITS OWN computation epoch — the
        # per-version fleet oracle over the stamped dispatch logs, each
        # replayed through a full-graph sampler built from that version's
        # topology snapshot. A row served at fleet version v may have
        # been COMPUTED at any epoch <= v (an un-invalidated cache entry
        # is exactly a pre-commit row whose closure the commits never
        # touched), so the candidate set is the union over epochs <= v —
        # never a future epoch, and never a cross-epoch mixture (each
        # oracle only collects rows stamped with its own version).
        oracles = {}
        for v, tv in enumerate(topo_vs):
            def mk(tv=tv):
                return GraphSageSampler(tv, sizes=SIZES, mode="TPU",
                                        seed=SEED)
            oracles[v] = replay_fleet_oracle(d_zs, model, params, mk, feat,
                                             graph_version=v)
        epoch_parity_rows = 0
        for node, row, v in zip(req_nodes, rows_zs, vers_zs):
            assert any(
                any(np.array_equal(row, c)
                    for c in oracles[v2].get(int(node), []))
                for v2 in range(v + 1)
            ), f"EPOCH PARITY VIOLATION at node {int(node)} version {v}"
            epoch_parity_rows += 1

        # run-twice bit-identity on the zero-stall storm: logits, router
        # and shard dispatch logs, and every epoch stamp, byte for byte
        d_zs2, rows_zs2, vers_zs2, _, _, drop2 = run_storm(False)
        assert drop2 == 0
        ident_bytes = 0
        assert vers_zs == vers_zs2
        for a, b in zip(rows_zs, rows_zs2):
            assert a.tobytes() == b.tobytes(), "RUN-TWICE LOGIT DIVERGENCE"
            ident_bytes += a.nbytes
        ents2 = log_entries(d_zs2)
        assert len(ents_zs) == len(ents2)
        for a, b in zip(ents_zs, ents2):
            assert a.tobytes() == b.tobytes(), \
                "RUN-TWICE DISPATCH-STATE DIVERGENCE"
            ident_bytes += a.nbytes

        # (b) SATURATED threaded traffic with a commit storm racing
        # in-flight flushes (max_in_flight=2): on-commit request latency
        # vs a frozen-graph twin, plus the fenced twin for contrast.
        # CONTROL (this is a 1-core loopback box): the commit BUILD is
        # off-fence but still burns CPU the clients would otherwise get,
        # so the frozen twin runs the SAME commit schedule against a
        # detached ballast engine that serves nothing — both twins pay
        # identical build CPU and the on-commit delta isolates the fence
        # discipline, which is the claim under test.
        TRAFFIC = args.stream_stall_traffic_requests
        STORM = args.stream_stall_storm_commits
        t_nodes = zipfian_trace(n, TRAFFIC, alpha=1.1, seed=47)
        storm_src = zipfian_trace(n, STORM * EPC, alpha=1.1, seed=53)
        storm_dst = rng_s.integers(0, n, STORM * EPC)
        warm_src = zipfian_trace(n, 2 * EPC, alpha=1.1, seed=59)
        warm_dst = rng_s.integers(0, n, 2 * EPC)

        def run_traffic(fenced, commits_on):
            d = build_storm(fenced)
            target = d if commits_on else build_storm(False)
            # two unmeasured commits so scatter-shape compiles never land
            # inside a measured window
            for k in range(2):
                target.stage_edges(warm_src[k * EPC:(k + 1) * EPC],
                                   warm_dst[k * EPC:(k + 1) * EPC])
                target.update_graph()
            lat, errs = [], []
            lock = threading.Lock()
            chunks = np.array_split(t_nodes, args.clients)

            def client(chunk):
                for node in chunk:
                    t0 = time.perf_counter()
                    try:
                        h = d.submit(int(node))
                        while not h.done() and d._drainable():
                            d.flush()
                        h.result(120)
                    except Exception as exc:
                        errs.append(repr(exc))
                        continue
                    with lock:
                        lat.append((t0, time.perf_counter()))

            windows = []
            threads = [threading.Thread(target=client, args=(c,))
                       for c in chunks]
            [t.start() for t in threads]
            for k in range(STORM):
                lo = k * EPC
                target.stage_edges(storm_src[lo:lo + EPC],
                                   storm_dst[lo:lo + EPC])
                c0 = time.perf_counter()
                target.update_graph()
                windows.append((c0, time.perf_counter()))
                time.sleep(0.02)
            [t.join() for t in threads]
            assert not errs, f"traffic errors: {errs}"
            return d, lat, windows

        def on_commit_lat(lat, windows):
            return [t1 - t0 for (t0, t1) in lat
                    if any(t0 < we and t1 > wb for (wb, we) in windows)]

        _, lat_fr, win_fr = run_traffic(False, commits_on=False)
        on_fr = on_commit_lat(lat_fr, win_fr)
        assert len(on_fr) >= 8, f"only {len(on_fr)} frozen-twin samples"
        p99_frozen = float(np.percentile(on_fr, 99))
        p99_frozen_all = float(np.percentile(
            [t1 - t0 for t0, t1 in lat_fr], 99))
        _, lat_tz, win_tz = run_traffic(False, commits_on=True)
        on_tz = on_commit_lat(lat_tz, win_tz)
        assert len(on_tz) >= 8, f"only {len(on_tz)} on-commit samples"
        p99_on_zs = float(np.percentile(on_tz, 99))
        _, lat_tf, win_tf = run_traffic(True, commits_on=True)
        on_tf = on_commit_lat(lat_tf, win_tf)
        p99_on_f = float(np.percentile(on_tf, 99)) if on_tf else None
        assert p99_on_zs <= 1.3 * p99_frozen, (
            f"ON-COMMIT P99 {p99_on_zs * 1e3:.2f} ms > 1.3x frozen-graph "
            f"{p99_frozen * 1e3:.2f} ms"
        )

        out = {
            "metric": "serve_probe_stream_stall",
            "git_revision": git_revision(),
            "backend": jax.devices()[0].platform,
            "config": {
                "commits": COMMITS, "requests_per_commit": RPC,
                "edges_per_commit": EPC, "alpha": 1.1, "hosts": 2,
                "max_batch": args.max_batch, "sizes": SIZES, "nodes": n,
                "traffic_requests": TRAFFIC, "storm_commits": STORM,
                "clients": args.clients,
            },
            "note": (
                "sequential storm is a deterministic drive (stall "
                "means are 1-core loopback walls, read the ratio); "
                "fenced-twin bit-parity, >=10x stall collapse, "
                "epoch-aware oracle parity, run-twice bit-identity, "
                "zero drops and on-commit p99 <=1.3x frozen-graph are "
                "asserted in-run — a written artifact means they held. "
                "The frozen twin runs the same commit schedule against "
                "a detached ballast engine (1-core control: both twins "
                "pay identical off-fence build CPU, so the on-commit "
                "delta isolates the fence discipline)"
            ),
            "storm": {
                "commit_stall_us_fenced": {
                    "mean": round(mean_f, 1),
                    "max": round(max(stalls_f), 1),
                },
                "commit_stall_us_zerostall": {
                    "mean": round(mean_zs, 1),
                    "max": round(max(stalls_zs), 1),
                },
                "stall_reduction_x": round(stall_ratio, 1),
                "stall_hist_zerostall": (
                    d_zs.stats.commit_stall.snapshot()
                ),
                "served_rows": len(rows_zs),
                "epoch_parity_rows": epoch_parity_rows,
                "graph_versions_served": sorted(set(vers_zs)),
                "graph_version_end": d_zs.graph_version,
                "run_twice_identical_bytes": ident_bytes,
                "dropped_requests": 0,
            },
            "saturated_traffic": {
                "p99_ms_frozen_ballast_windows": round(p99_frozen * 1e3, 3),
                "p99_ms_frozen_all": round(p99_frozen_all * 1e3, 3),
                "on_commit_p99_ms_zerostall": round(p99_on_zs * 1e3, 3),
                "on_commit_p99_ms_fenced": (
                    round(p99_on_f * 1e3, 3)
                    if p99_on_f is not None else None
                ),
                "on_commit_vs_frozen_x": round(p99_on_zs / p99_frozen, 3),
                "on_commit_samples_frozen": len(on_fr),
                "on_commit_samples_zerostall": len(on_tz),
                "on_commit_samples_fenced": len(on_tf),
            },
        }
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        return

    # -- round-21 graph-lifecycle soak (--lifecycle -> LIFECYCLE_r01.json) ---
    if args.lifecycle:
        from quiver_tpu.stream import StreamingTiledGraph
        from quiver_tpu.workloads import (
            TemporalServeEngine,
            TemporalTiledGraph,
            quantize_t,
            replay_temporal_log,
        )

        MAXD = 512
        REC, QUANT = 0.02, 0.05
        T0, DT = 50.0, 1.0
        W = args.lifecycle_window_commits * DT
        EPC = args.lifecycle_edges_per_commit
        COMMITS = args.lifecycle_commits

        rng_lc = np.random.default_rng(123)
        E = topo.indices.shape[0]
        base_ts = rng_lc.uniform(0.0, 50.0, E).astype(np.float32)

        stream_lc = StreamingTiledGraph(topo, reserve_frac=1.0,
                                        edge_ts=base_ts)
        # pre-size the reserve for the steady-state live set: the window
        # holds window_commits*EPC streamed lanes, plus one partial tile
        # row per touched node and spill-chain slack. NO auto-provision
        # backstop is configured below — a StreamCapacityError anywhere
        # in the soak fails the probe, which is the acceptance pin.
        live_lanes = args.lifecycle_window_commits * EPC
        want_rows = 4 * (live_lanes // 128 + 1) + 2 * n
        if stream_lc.free_rows < want_rows:
            stream_lc.provision_reserve(want_rows - stream_lc.free_rows)

        s_lc = GraphSageSampler(topo, sizes=SIZES, mode="TPU", seed=SEED,
                                dedup=False, max_deg=MAXD)
        s_lc.bind_temporal(stream_lc, recency=REC)
        eng = TemporalServeEngine(
            model, params, s_lc, feat,
            ServeConfig(max_batch=args.max_batch,
                        buckets=(8, args.max_batch), max_delay_ms=1e9,
                        record_dispatches=True,
                        stream_retention_window=W,
                        stream_compact_min_reclaim=8,
                        stream_provision_tiles=0),
            t_quantum=QUANT,
        )
        eng.warmup()

        total_edges = COMMITS * EPC
        app_src = zipfian_trace(n, total_edges, alpha=1.1, seed=31)
        app_dst = rng_lc.integers(0, n, total_edges)
        qry = zipfian_trace(n, COMMITS * args.lifecycle_requests_per_commit,
                            alpha=1.1, seed=17)
        RM_PER = max(EPC // 100, 1)  # deletes ride along every commit

        occ, dropped, cap_errors, parity_rows = [], 0, 0, 0
        compact_passes = rows_reclaimed = 0
        prev_batch = None
        t_wall0 = time.perf_counter()
        for k in range(COMMITS):
            lo = k * EPC
            src_k = app_src[lo:lo + EPC]
            dst_k = app_dst[lo:lo + EPC]
            # commit-k arrivals land inside (T0+k*DT, T0+(k+1)*DT]
            ts_k = (T0 + k * DT
                    + (np.arange(EPC) + 1.0) / EPC * DT).astype(np.float32)
            eng.stage_edges(src_k, dst_k, ts=ts_k)
            if prev_batch is not None:
                # delete a slice of LAST commit's arrivals — live, well
                # inside the window, exercising lane-shift removal under
                # traffic (each picked index is one appended copy, so
                # existence holds even across duplicate pairs)
                eng.stage_removals(prev_batch[0][:RM_PER],
                                   prev_batch[1][:RM_PER])
            prev_batch = (src_k, dst_k)
            try:
                eng.update_graph()  # retention expires at commit time
            except Exception as exc:
                cap_errors += 1
                raise AssertionError(
                    f"LIFECYCLE: commit {k} failed ({exc!r})"
                ) from exc
            tc = T0 + (k + 1) * DT

            # live Zipf traffic between commits
            qlo = k * args.lifecycle_requests_per_commit
            nodes_k = qry[qlo:qlo + args.lifecycle_requests_per_commit]
            try:
                eng.predict([int(x) for x in nodes_k], t=tc + 0.5 * DT)
            except Exception:
                dropped += 1

            occ.append(int(stream_lc.reserve_report()["reserve_used"]))

            if (k + 1) % args.lifecycle_compact_every == 0:
                cs = eng.compact_graph()
                compact_passes += 1
                rows_reclaimed += cs["tiles_reclaimed"]

            if (k + 1) % args.lifecycle_parity_every == 0:
                # in-run oracle parity at serving grain: rows served NOW
                # must bit-match a fresh rebuild of the live stream
                # ((topo, ts) materialized in tile-lane order) replayed
                # through a twin sampler with a synced key stream
                call0 = s_lc._call
                off = len(eng.dispatch_log)
                tq = tc + 0.25 * DT
                chk_nodes = [int(x) for x in nodes_k]
                rows = eng.predict(chk_nodes, t=tq)
                topo2, ts2 = stream_lc.adj.to_temporal()
                s2 = GraphSageSampler(topo2, sizes=SIZES, mode="TPU",
                                      seed=SEED, dedup=False, max_deg=MAXD)
                s2.bind_temporal(TemporalTiledGraph(
                    topo2, ts2, id_dtype=stream_lc.tiles.dtype), recency=REC)
                s2._call = call0
                oracle = replay_temporal_log(
                    eng.dispatch_log[off:], model, params, s2, feat)
                kq = float(np.float32(quantize_t(tq, QUANT)))
                for node, row in zip(chk_nodes, rows):
                    assert any(np.array_equal(row, c)
                               for c in oracle.get((node, kq), [])), \
                        f"LIFECYCLE PARITY VIOLATION at node {node}"
                    parity_rows += 1
        wall = time.perf_counter() - t_wall0

        assert dropped == 0, f"{dropped} dropped requests under lifecycle"
        assert cap_errors == 0
        assert parity_rows > 0
        # flat occupancy: once the window has filled (plus one compaction
        # period for the first trim), reserve consumption stops trending —
        # expired lanes are reused in place and compaction returns spill
        # waste, so the band stays within 25% of its floor
        warm = 2 * args.lifecycle_window_commits + args.lifecycle_compact_every
        assert warm < COMMITS, "soak too short for a steady-state claim"
        steady = occ[warm:]
        band = max(steady) - min(steady)
        # "flat" means BOUNDED AND NOT LINEARLY TRENDING, not
        # saw-tooth-free: between compaction passes spills accumulate and
        # each pass trims them back, and the per-cycle floor carries the
        # one growth in-place expiry cannot reclaim — a hot node's
        # high-water footprint (interior dead lanes under a live tail
        # stay allocated; shifting live lanes would break the
        # observe-only pin), a running max that creeps ~log(t). A LEAK
        # is linear: appends permanently outrunning expiry+trim would
        # add live_lanes/window rows per window. Pin the distinction
        # three ways: the floor creep over the whole soak stays inside
        # the high-water envelope (<= 50% over the first cycle's floor),
        # occupancy never exceeds the provisioned live-set bound, and
        # the projected reserve runway (from measured creep) is >= 20
        # soaks long.
        per = args.lifecycle_compact_every
        floors = [min(steady[i:i + per]) for i in range(0, len(steady), per)]
        trace = ",".join(str(x) for x in occ[::max(len(occ) // 50, 1)])
        assert floors[-1] <= floors[0] + max(16, int(0.5 * floors[0])), \
            f"LIFECYCLE: occupancy floor climbing {floors} (occ {trace})"
        assert band <= max(32, 2 * per + int(0.5 * min(steady))), \
            f"LIFECYCLE: occupancy not flat (band {band} rows over " \
            f"[{min(steady)}, {max(steady)}]; floors {floors}; occ {trace})"
        assert max(occ) <= want_rows, \
            f"LIFECYCLE: occupancy {max(occ)} exceeded live-set bound " \
            f"{want_rows}"
        runway = stream_lc.reserve_report()["projected_commits_to_exhaustion"]
        assert runway is None or runway >= 20 * COMMITS, \
            f"LIFECYCLE: reserve runway {runway} commits < 20 soaks"

        rep_end = stream_lc.reserve_report()
        out = {
            "metric": "serve_probe_lifecycle",
            "git_revision": git_revision(),
            "backend": jax.devices()[0].platform,
            "config": {
                "commits": COMMITS, "edges_per_commit": EPC,
                "window_commits": args.lifecycle_window_commits,
                "requests_per_commit": args.lifecycle_requests_per_commit,
                "compact_every": args.lifecycle_compact_every,
                "parity_every": args.lifecycle_parity_every,
                "removals_per_commit": RM_PER, "alpha": 1.1,
                "max_batch": args.max_batch, "sizes": SIZES, "nodes": n,
                "recency": REC, "t_quantum": QUANT,
            },
            "note": (
                "sequential deterministic soak (walls are 1-core loopback, "
                "read the structure); zero-drop, zero StreamCapacityError "
                "(no auto-provision backstop configured), bounded "
                "non-trending occupancy (saw-tooth trimmed per compaction "
                "cycle; floor creep inside the hot-node high-water "
                "envelope; >=20-soak projected runway), and fresh-rebuild "
                "oracle parity are asserted in-run — a written artifact "
                "means they held"
            ),
            "edges_appended": int(eng.stats.delta_edges),
            "edges_expired": int(eng.stats.edges_expired),
            "edges_deleted": int(eng.stats.edges_deleted),
            "commits": COMMITS,
            "graph_version": eng.graph_version,
            "compaction_passes": compact_passes,
            "tile_rows_reclaimed": rows_reclaimed,
            "parity_rows": parity_rows,
            "dropped_requests": dropped,
            "capacity_errors": cap_errors,
            "occupancy_rows": {
                "at_warmup": occ[warm - 1], "steady_min": min(steady),
                "steady_max": max(steady), "end": occ[-1],
                "band": band, "cycle_floors": floors,
            },
            "reserve_report": rep_end,
            "edges_per_s": round(total_edges / wall, 1),
            "wall_s": round(wall, 1),
        }
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        return

    # -- round-16 elastic-fleet leg (--scale -> SERVE_r08.json) --------------
    if args.scale:
        from quiver_tpu.parallel.scaling import (
            fleet_table, format_fleet_markdown, pick_fleet_action,
        )
        from quiver_tpu.trace import WorkloadConfig as _WC

        RAMP = (2, 4, 2)

        def build_elastic(**kw):
            """Host-mode hosts=1 fleet, closure residency (the fused
            owner path live resharding rides), sketches on so the fleet
            can SEE its own load."""
            shard_cfg = ServeConfig(
                max_batch=args.max_batch, buckets=(8, args.max_batch),
                max_delay_ms=2.0, record_dispatches=True,
            )
            cfg = DistServeConfig(
                hosts=1, max_batch=args.max_batch, max_delay_ms=2.0,
                record_dispatches=True, shard_config=shard_cfg,
                exchange="host", migrate_batch_seeds=args.migrate_batch,
                workload=_WC(topk=64), **kw,
            )
            dist = DistServeEngine.build(
                model, params, topo, feat, SIZES, hosts=1, config=cfg,
                sampler_seed=SEED,
            )
            dist.warmup()
            dist.reset_stats()
            return dist

        def serve_seq(dist, trace, timeout=300):
            # array-at-a-time replay (round 20): submit_many makes the
            # same admission decisions as the per-request loop, pinned
            handles = dist.submit_many(np.asarray(trace, np.int64))
            while dist._drainable():
                dist.flush()
            out = []
            for h in handles:
                try:
                    out.append(h.result(timeout))
                except Exception as exc:
                    out.append(exc)
            return out

        trace_s = zipfian_trace(n, args.scale_requests, alpha=1.1, seed=61)

        def ramp(fault_specs=(), **kw):
            """Drive one wave per fleet size across the 1->RAMP ramp,
            scaling live between waves. Returns everything the parity
            and replay comparisons need."""
            inj = FaultInjector(fault_specs) if fault_specs else None
            dist = build_elastic(
                fault_injector=inj,
                full_graph_fallback=bool(fault_specs), **kw,
            )
            waves, walls, summaries = [], [], []
            t0 = time.perf_counter()
            waves.append(serve_seq(dist, trace_s))
            walls.append(time.perf_counter() - t0)
            for h in RAMP:
                summaries.append(dist.scale(h))
                t0 = time.perf_counter()
                waves.append(serve_seq(dist, trace_s))
                walls.append(time.perf_counter() - t0)
            return dist, inj, waves, walls, summaries

        def parity_and_drops(dist, waves):
            oracle = replay_fleet_oracle(
                dist, model, params, make_full_sampler, feat
            )
            dropped = checked = 0
            for w in waves:
                for nid, row in zip(trace_s, w):
                    if isinstance(row, Exception):
                        dropped += 1
                        continue
                    assert any(
                        np.array_equal(row, c) for c in oracle[int(nid)]
                    ), f"SCALE-PARITY VIOLATION at node {int(nid)}"
                    checked += 1
            return checked, dropped

        # (a) THE acceptance leg: clean 1->2->4->2 ramp under the live
        # Zipf trace — ZERO dropped requests, bit-parity of every
        # completed row against the epoch-aware fleet oracle, asserted
        # in-run
        dist_c, _, waves_c, walls_c, summaries_c = ramp()
        # one more wave at the SETTLED hosts=2 fleet with fresh owner
        # clocks: fleet_table (leg c) prices dispatch from the final
        # fleet's per-owner routed-leg mean — a whole-ramp wall would
        # average four fleet sizes and fold in router/submit overhead.
        # Drop the router result cache first or the repeated trace is
        # absorbed before it ever times an owner leg.
        dist_c.cache.invalidate()
        dist_c.workload.owners.clear()
        t0 = time.perf_counter()
        waves_c.append(serve_seq(dist_c, trace_s))
        walls_c.append(time.perf_counter() - t0)
        checked, dropped = parity_and_drops(dist_c, waves_c)
        assert dropped == 0, f"{dropped} dropped requests on a clean ramp"
        assert checked == len(waves_c) * trace_s.size
        assert sum(s["rollbacks"] for s in summaries_c) == 0
        assert sorted(dist_c.engines) == [0, 1]  # shrink retired 2 hosts
        clean_leg = {
            "ramp": [1] + list(RAMP),
            "requests_per_wave": int(trace_s.size),
            "migrate_batch_seeds": args.migrate_batch,
            "migration_batches": dist_c.stats.migration_batches,
            "migrated_seeds": dist_c.stats.migrated_seeds,
            "ownership_epochs": dist_c.ownership_epoch,
            "retired_engines": len(dist_c._retired_engines),
            "dropped_requests": dropped,
            "parity_rows_checked": checked,
            "wave_qps": [
                round(trace_s.size / w, 1) for w in walls_c[:len(RAMP) + 1]
            ],
            "settled_wave_qps": round(trace_s.size / walls_c[-1], 1),
            "scale_summaries": summaries_c,
            "epoch_history_head": dist_c.routing_epochs()[:6],
        }

        # (b) owner kill MID-MIGRATION, replayable by construction: owner
        # 1 dies at migration batch index 3 (a source-side kill during
        # the 2->4 step) — in-flight ranges roll forward/back
        # deterministically, the fallback absorbs the dead owner's
        # traffic (zero dropped), parity still holds, and the identical
        # faulty run replays bit-identically
        KILL = (FaultSpec(owner=1, fid=3, kind="kill", at="migration"),)
        dist_k, inj_k, waves_k, _, summaries_k = ramp(
            KILL, eject_after=1, eject_backoff_flushes=64
        )
        checked_k, dropped_k = parity_and_drops(dist_k, waves_k)
        assert dropped_k == 0, "fallback should absorb the dead owner"
        assert inj_k.migration_events(), "migration fault never fired"
        outcomes_k = [e[-1] for e in dist_k.migration_log]
        assert ("rollforward" in outcomes_k or "rollback" in outcomes_k)
        dist_k2, inj_k2, waves_k2, _, _ = ramp(
            KILL, eject_after=1, eject_backoff_flushes=64
        )
        assert dist_k2.migration_log == dist_k.migration_log
        assert inj_k2.migration_events() == inj_k.migration_events()
        replay_identical = all(
            (isinstance(a, Exception) and isinstance(b, Exception))
            or np.array_equal(a, b)
            for wa, wb in zip(waves_k, waves_k2)
            for a, b in zip(wa, wb)
        )
        assert replay_identical, "faulty ramp did not replay bit-identical"
        kill_leg = {
            "fault": {"owner": 1, "migration_batch": 3, "kind": "kill"},
            "dropped_requests": dropped_k,
            "parity_rows_checked": checked_k,
            "migration_outcomes": outcomes_k,
            "migration_fault_events": inj_k.migration_events(),
            "hedges": dist_k.stats.hedges,
            "migration_rollbacks": dist_k.stats.migration_rollbacks,
            "migration_rollforwards": dist_k.stats.migration_rollforwards,
            "replay_bit_identical": replay_identical,
            "hosts_after": dist_k.hosts,
            "incomplete_hosts": summaries_k[-1].get("incomplete_hosts"),
        }

        # (c) price the next move: add-a-host vs replicate-the-head from
        # the clean ramp's MEASURED coverage curve + the settled fleet's
        # per-owner routed-leg mean (the r15 skew-leg sourcing — the
        # monitor's owner clocks were reset before the settled wave, so
        # only the final hosts=2 legs are in the mean)
        cov = dist_c.workload.skew_report(top_ks=(1, 8, 16, 64))[
            "top_coverage"
        ]
        owner_lat = dist_c.workload_report()["router"]["owners"][
            "per_owner"
        ]
        dispatch_s = (
            sum(v["lat_mean_ms"] for v in owner_lat.values())
            / max(len(owner_lat), 1) / 1e3
        ) or 1e-3
        fleet_rows = fleet_table(
            sorted((int(k), float(v)) for k, v in cov.items()),
            hosts=dist_c.hosts, bucket=args.max_batch,
            out_dim=model.out_dim, dispatch_s=dispatch_s,
            table_rows=n, feature_dim=feat.shape[1],
        )
        # 5% uplift floor: below that the "win" is wire noise on this
        # loopback box, and churn costs more than it buys
        pick = pick_fleet_action(fleet_rows, min_uplift=1.05)
        print(format_fleet_markdown(fleet_rows))

        out = {
            "metric": "serve_probe_scale",
            "git_revision": git_revision(),
            "backend": jax.devices()[0].platform,
            "config": {
                "ramp": [1] + list(RAMP), "alpha": 1.1,
                "requests_per_wave": int(trace_s.size),
                "max_batch": args.max_batch,
                "migrate_batch_seeds": args.migrate_batch,
                "exchange": "host",
            },
            "note": (
                "sequential deterministic drive (QPS numbers are "
                "1-core loopback walls, read the structure not the "
                "absolute); parity/zero-drop asserts are in-run — a "
                "written artifact means they held"
            ),
            "clean_ramp": clean_leg,
            "kill_mid_migration": kill_leg,
            "fleet_table": {
                "measured_dispatch_s": dispatch_s,
                "rows": [r._asdict() for r in fleet_rows],
                "pick": pick._asdict() if pick else None,
            },
        }
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        return

    # -- round-15 fleet-robustness leg (--faults -> SERVE_r07.json) ----------
    if args.faults:
        from quiver_tpu.parallel.scaling import (
            format_skew_markdown, pick_replication_k, skew_table,
        )
        from quiver_tpu.trace import WorkloadConfig as _WC

        HOSTS = 2
        alpha = 1.3

        def build_fleet(**kw):
            """Host-mode routed fleet (per-owner legs individually
            addressable — the hedging/fault surface) with the standard
            2-bucket shard ladder."""
            shard_cfg = ServeConfig(
                max_batch=args.max_batch, buckets=(8, args.max_batch),
                max_delay_ms=2.0, record_dispatches=True,
            )
            cfg = DistServeConfig(
                hosts=HOSTS, max_batch=args.max_batch, max_delay_ms=2.0,
                record_dispatches=True, shard_config=shard_cfg,
                exchange="host", **kw,
            )
            dist = DistServeEngine.build(
                model, params, topo, feat, SIZES, hosts=HOSTS, config=cfg,
                sampler_seed=SEED,
            )
            dist.warmup()
            dist.reset_stats()
            return dist

        def serve_seq(dist, trace, timeout=300):
            """Deterministic array-at-a-time drive; returns
            (rows|exceptions) per request — predict() would re-raise the
            first per-request error, and the parity comparison wants
            every outcome."""
            handles = dist.submit_many(np.asarray(trace, np.int64))
            while dist._drainable():
                dist.flush()
            out = []
            for h in handles:
                try:
                    out.append(h.result(timeout))
                except Exception as exc:
                    out.append(exc)
            return out

        def oracle_check(dist, trace, rows):
            """Every COMPLETED row must bit-match a fault-free offline
            replay candidate of the fleet's dispatch logs."""
            oracle = replay_fleet_oracle(
                dist, model, params, make_full_sampler, feat
            )
            checked = 0
            for nid, row in zip(trace, rows):
                if isinstance(row, Exception):
                    continue
                assert any(
                    np.array_equal(row, c) for c in oracle[int(nid)]
                ), f"FAULT-PARITY VIOLATION at node {int(nid)}"
                checked += 1
            return checked

        trace_f = zipfian_trace(n, args.fault_requests, alpha=alpha, seed=51)

        # (a) THE acceptance leg: kill owner 0 mid-flush, fallback up.
        # Run the identical faulty run twice: completed rows bit-identical
        # across runs AND bit-identical to the offline replay; hedges > 0;
        # errors (there are none here — the fallback absorbs) per-request.
        def kill_run():
            inj = FaultInjector([FaultSpec(owner=0, fid=3, kind="kill")])
            dist = build_fleet(fault_injector=inj, full_graph_fallback=True,
                               eject_after=1, eject_backoff_flushes=8)
            rows = serve_seq(dist, trace_f)
            return dist, rows, inj

        dist_k, rows_k, inj_k = kill_run()
        assert not any(isinstance(r, Exception) for r in rows_k)
        parity_rows = oracle_check(dist_k, trace_f, rows_k)
        sk = dist_k.stats
        assert sk.hedges > 0, "hedged re-route path not exercised"
        assert sk.owner_ejections >= 1, sk.snapshot()
        assert inj_k.events() and inj_k.events()[0][1] == 0
        dist_k2, rows_k2, inj_k2 = kill_run()
        assert dist_k2.hedge_events() == dist_k.hedge_events()
        assert inj_k2.events() == inj_k.events()
        replay_identical = all(
            np.array_equal(a, b) for a, b in zip(rows_k, rows_k2)
        )
        assert replay_identical, "faulty run did not replay bit-identical"
        kill_leg = {
            "fault": {"owner": 0, "fid": 3, "kind": "kill"},
            "requests": int(trace_f.size),
            "alpha": alpha,
            "parity_rows_checked": parity_rows,
            "completed": int(trace_f.size),
            "hedges": sk.hedges,
            "hedged_seeds": sk.hedged_seeds,
            "hedge_ejected": sk.hedge_ejected,
            "owner_ejections": sk.owner_ejections,
            "request_errors": sk.request_errors,
            "replay_bit_identical": replay_identical,
            "hedge_events_head": dist_k.hedge_events()[:8],
        }

        # (a') error isolation with NO failover target: the dead owner's
        # requests error per-request, everything else completes, the
        # engine never dies — availability is the surviving share
        inj_iso = FaultInjector([FaultSpec(owner=0, fid=1, kind="kill")])
        dist_iso = build_fleet(fault_injector=inj_iso, eject_after=1,
                               eject_backoff_flushes=8)
        rows_iso = serve_seq(dist_iso, trace_f)
        n_err = sum(1 for r in rows_iso if isinstance(r, Exception))
        assert 0 < n_err < trace_f.size, (n_err, trace_f.size)
        oracle_check(dist_iso, trace_f, rows_iso)
        iso_leg = {
            "fault": {"owner": 0, "fid": 1, "kind": "kill"},
            "no_failover_target": True,
            "requests": int(trace_f.size),
            "errored_per_request": n_err,
            "completed": int(trace_f.size) - n_err,
            "availability": round(1.0 - n_err / trace_f.size, 4),
            "hedge_failed": dist_iso.stats.hedge_failed,
            "engine_survived": True,  # serve_seq finished every flush
        }

        # (b) availability + p99 vs hedge deadline under STALL faults
        # (seeded stalls of 150 ms), fallback up, threaded saturated
        # drive, median-of-3 per point (NEXT.md noise discipline)
        stall_s = 0.15
        deadlines = [float(d) for d in args.hedge_deadlines.split(",")]

        def stall_run(deadline_ms):
            inj = FaultInjector.seeded(
                owners=range(HOSTS), n_faults=6, seed=23,
                fid_range=(2, 14), kinds=("stall",), stall_s=stall_s,
            )
            dist = build_fleet(fault_injector=inj, full_graph_fallback=True,
                               hedge_deadline_ms=deadline_ms)
            chunks = np.array_split(trace_f, args.clients)
            results = {}

            def client(tid, chunk):
                rows = []
                for nid in chunk:
                    try:
                        rows.append(dist.submit(int(nid)).result(300))
                    except Exception as exc:
                        rows.append(exc)
                results[tid] = rows

            t0 = time.perf_counter()
            with dist:
                threads = [threading.Thread(target=client, args=(i, c))
                           for i, c in enumerate(chunks)]
                [t.start() for t in threads]
                [t.join() for t in threads]
            wall = time.perf_counter() - t0
            all_rows = [r for tid in sorted(results) for r in results[tid]]
            ok = sum(1 for r in all_rows if not isinstance(r, Exception))
            s = dist.stats
            return {
                "qps": round(trace_f.size / wall, 1),
                "availability": round(ok / trace_f.size, 4),
                "p99_ms": round(s.latency.percentile(99), 3),
                "p50_ms": round(s.latency.percentile(50), 3),
                "hedge_timeouts": s.hedge_timeouts,
                "hedges": s.hedges,
            }

        stall_points = []
        for d in deadlines:
            reps = [stall_run(d) for _ in range(args.repeats)]
            stall_points.append({
                "hedge_deadline_ms": d,
                "stall_s": stall_s,
                "p99_ms": median_min_max([r["p99_ms"] for r in reps]),
                "availability": min(r["availability"] for r in reps),
                "qps": median_min_max([r["qps"] for r in reps]),
                "hedge_timeouts": max(r["hedge_timeouts"] for r in reps),
                "runs": reps,
            })
        # availability holds at 1.0 everywhere (fallback absorbs), and a
        # live deadline must actually fire hedges on timeouts
        assert all(p["availability"] == 1.0 for p in stall_points)
        armed = [p for p in stall_points if p["hedge_deadline_ms"] > 0
                 and p["hedge_deadline_ms"] < stall_s * 1e3]
        assert all(p["hedge_timeouts"] > 0 for p in armed), stall_points

        # (c) hot-set replication uplift vs the skew_table prediction:
        # warm the router sketch, replicate the measured head, interleaved
        # median-of-3 off/on saturated runs; the structural claim (head
        # seeds leave the owner legs) asserts deterministically, the QPS
        # medians report with spread
        def repl_run(replicate):
            dist = build_fleet(router_cache_entries=0,
                               workload=_WC(topk=256))
            # sketch warm-up on the SAME trace the measured window
            # serves (steady-state assumption: the head the sketch saw
            # is the head the replica will face; zipfian_trace permutes
            # the node mapping per seed, so a different seed would hand
            # the replica the wrong head)
            dist.predict(trace_f, timeout=300)
            rep_info = None
            if replicate:
                rep_info = dist.refresh_replicas(k=args.replicate_k)
            cov_meas = dist.workload.skew_report(
                top_ks=(1, 8, args.replicate_k, 64)
            )["top_coverage"]
            dist.reset_stats()
            log_start = len(dist.dispatch_log)
            chunks = np.array_split(trace_f, args.clients)
            errors = []

            def client(chunk):
                try:
                    dist.predict(chunk, timeout=300)
                except Exception as exc:
                    errors.append(repr(exc))

            t0 = time.perf_counter()
            with dist:
                threads = [threading.Thread(target=client, args=(c,))
                           for c in chunks]
                [t.start() for t in threads]
                [t.join() for t in threads]
            wall = time.perf_counter() - t0
            if errors:
                raise RuntimeError(f"replication clients failed: {errors}")
            if replicate:
                # THE structural claim, exact and deterministic: after
                # the refresh, no owner sub-batch ever carried a
                # replicated seed — head traffic never reached the
                # exchange path
                rep_set = dist.replica.id_set
                for _, split in dist.dispatch_log[log_start:]:
                    for h, ids in split:
                        if h != REPLICA_HOST:
                            leaked = [i for i in ids if int(i) in rep_set]
                            assert not leaked, (h, leaked)
            s = dist.stats
            owner_seeds = sum(
                v for h, v in s.sub_batch_seeds.items() if h != REPLICA_HOST
            )
            return {
                "qps": round(trace_f.size / wall, 1),
                "p99_ms": round(s.latency.percentile(99), 3),
                "replica_hits": s.replica_hits,
                "owner_routed_seeds": owner_seeds,
                "routed_seeds": s.routed_seeds,
                "coverage": cov_meas,
                "replica": rep_info,
            }

        runs_off, runs_on = [], []
        for _ in range(args.repeats):
            runs_off.append(repl_run(False))
            runs_on.append(repl_run(True))
        qps_off = median_min_max([r["qps"] for r in runs_off])
        qps_on = median_min_max([r["qps"] for r in runs_on])
        measured_uplift = qps_on["median"] / qps_off["median"]
        # the replica actually absorbed traffic (the exact head-seeds-
        # never-reach-an-owner claim asserted per dispatch-log entry
        # inside repl_run; REQUEST-grain coverage is the sketch number,
        # ROUTED-seed share is structurally flatter — router coalescing
        # collapses the head's repeats into single routed seeds)
        on = runs_on[-1]
        head_share = on["replica_hits"] / max(on["routed_seeds"], 1)
        assert on["replica_hits"] > 0
        # the skew_table prediction from the SAME measured coverage curve
        # (wire-term model: exchange seconds saved per routed flush); in
        # host mode there is no DCN, so report the prediction beside the
        # measurement rather than asserting equality
        dispatch_s = 2e-3
        rep_rows = skew_table(
            sorted((int(k), float(v)) for k, v in on["coverage"].items()),
            hosts=HOSTS, bucket=args.max_batch, out_dim=model.out_dim,
            dispatch_s=dispatch_s, feature_dim=feat.shape[1],
        )
        pick = pick_replication_k(rep_rows, min_uplift=1.0)
        print(format_skew_markdown(rep_rows))
        repl_leg = {
            "replicate_k": args.replicate_k,
            "qps_off": qps_off, "qps_on": qps_on,
            "qps_runs_off": [r["qps"] for r in runs_off],
            "qps_runs_on": [r["qps"] for r in runs_on],
            "measured_uplift_median": round(measured_uplift, 4),
            "replica_head_share_of_routed": round(head_share, 4),
            "measured_topk_coverage": on["coverage"],
            "p99_off_ms": median_min_max([r["p99_ms"] for r in runs_off]),
            "p99_on_ms": median_min_max([r["p99_ms"] for r in runs_on]),
            "replica_hits": on["replica_hits"],
            "skew_table_predicted": [r._asdict() for r in rep_rows],
            "skew_table_pick": pick._asdict() if pick else None,
            "note": (
                "skew_table prices the WIRE term (DCN exchange seconds "
                "saved); this loopback host-mode box has no wire, so the "
                "honest read is the structural head-share assert + the "
                "QPS medians with spread — the predicted uplift is what "
                "a real pod's exchange would add on top"
            ),
        }

        out = {
            "metric": "serve_probe_faults",
            "git_revision": git_revision(),
            "backend": jax.devices()[0].platform,
            "config": {
                "hosts": HOSTS, "alpha": alpha,
                "requests": int(trace_f.size),
                "max_batch": args.max_batch, "clients": args.clients,
                "repeats": args.repeats, "exchange": "host",
            },
            "note": (
                "median-of-N with min/max per point (NEXT.md noise "
                "discipline); parity/availability asserts are in-run — a "
                "written artifact means they held"
            ),
            "owner_kill": kill_leg,
            "error_isolation_no_target": iso_leg,
            "hedge_deadline_sweep": stall_points,
            "replication": repl_leg,
        }
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        return

    # -- round-18 real-disk predictive-IO leg (--tiers --real-disk ->
    # TIER_r02.json) ---------------------------------------------------------
    if args.tiers and args.real_disk:
        import tempfile

        from quiver_tpu import Feature
        from quiver_tpu.pipeline import AsyncReadPool
        from quiver_tpu.tiers import (
            DiskShard,
            drop_page_cache,
            o_direct_supported,
        )

        # the r01 tier graph: 32 communities x 150 nodes, [4, 4] fanout —
        # row-level access head compact enough for the fast tiers to hold
        t_edges, tfeat, tn = community_graph(
            n_comm=32, per_comm=150, intra=6, dim=32, seed=5
        )
        ttopo = CSRTopo(edge_index=t_edges)
        T_SIZES = [4, 4]

        def make_tier_sampler():
            return GraphSageSampler(ttopo, sizes=T_SIZES, mode="TPU",
                                    seed=SEED)

        ROWB = tfeat.shape[1] * 4
        HBM_B = args.rd_hbm_rows * ROWB
        HOST_B = args.rd_host_rows * ROWB
        READ_WORKERS = 4
        tdir = tempfile.mkdtemp(prefix="qt_realdisk_")
        rng = np.random.default_rng(7)

        # capacity acceptance: the r02 claim is >=10x the DRAM budget
        table_bytes = tn * ROWB
        capacity_ratio = table_bytes / HOST_B
        assert capacity_ratio >= 10.0, (
            f"table {table_bytes}B is only {capacity_ratio:.1f}x the "
            f"host budget {HOST_B}B — raise n or shrink --rd-host-rows"
        )

        # alpha-1.3 trace whose HOT SET SHIFTS mid-run: two independent
        # hotness permutations, spliced at the halfway mark. The warm
        # third (placement adaptation) sees only the FIRST head, so the
        # frozen placement is misaligned with the second — the drift
        # regime flush-ahead prefetch exists for (the reactive r14 tier
        # pays the new head's disk reads inside the serve path).
        reqs = args.rd_requests
        half = reqs // 2
        perm_a, perm_b = rng.permutation(tn), rng.permutation(tn)
        trace = np.concatenate([
            perm_a[zipfian_trace(tn, half, alpha=1.3, seed=31)],
            perm_b[zipfian_trace(tn, reqs - half, alpha=1.3, seed=32)],
        ]).astype(np.int64)
        warm_n = reqs // 3
        assert warm_n < half, "warm window must end before the shift"

        # -- page-cache defeat: method probed EMPIRICALLY, recorded ------
        probe_rows = rng.standard_normal((256, tfeat.shape[1])) \
            .astype(np.float32)
        probe_sh = DiskShard.create(os.path.join(tdir, "probe.npy"),
                                    probe_rows)
        use_direct = o_direct_supported(probe_sh.path)
        method = ("o_direct" if use_direct
                  else "posix_fadvise_dontneed_between_legs")

        class _DeviceModelShard:
            """Defeated backing + the RECORDED per-row device-latency
            model (--rd-device-us): every read_block sleeps rows*us on
            the calling pool worker (GIL-releasing, so reads overlap
            like real IO). Applied identically to in-path gathers AND
            prefetch staging reads — the model can never flatter the
            prefetch arm. Bytes untouched."""

            def __init__(self, shard, us_per_row):
                self._shard = shard
                self._us = float(us_per_row)

            def __getattr__(self, name):
                return getattr(self._shard, name)

            def read_block(self, ids):
                out = self._shard.read_block(ids)
                n = np.asarray(ids).size
                if n and self._us > 0:
                    time.sleep(n * self._us * 1e-6)
                return out

            def read_rows(self, local_ids, pool=None):
                ids = np.asarray(local_ids, np.int64).reshape(-1)
                if pool is None or ids.size == 0:
                    return self.read_block(ids)
                return pool.gather(self.read_block, ids)

        def defeat(shard, device_us=0.0):
            """Swap a store backing onto the defeated read path (or, on
            filesystems refusing O_DIRECT, drop its pages — best-effort,
            recorded as such), plus the device model when asked."""
            out = DiskShard(shard.path, direct=True) if use_direct else shard
            if not use_direct:
                shard.drop_cache()
            if device_us > 0:
                out = _DeviceModelShard(out, device_us)
            return out

        # defeat EVIDENCE: per-row cold cost vs the page-cache-warm
        # memmap read of the same rows — the artifact must show the
        # defeat actually defeated something on this box
        pids = rng.integers(0, 256, 512)
        probe_sh.read_block(pids)
        t0 = time.perf_counter()
        for _ in range(3):
            probe_sh.read_block(pids)
        warm_us = (time.perf_counter() - t0) / 3 / pids.size * 1e6
        cold_sh = defeat(probe_sh)
        cold_sh.read_block(pids[:8])
        t0 = time.perf_counter()
        for _ in range(3):
            cold_sh.read_block(pids)
        cold_us = (time.perf_counter() - t0) / 3 / pids.size * 1e6

        def build_feature(name, device_us=0.0):
            f = Feature(
                rank=0, device_cache_size=HBM_B, host_memory_budget=HOST_B,
                disk_path=os.path.join(tdir, name), adaptive_tiers=True,
                read_pool=AsyncReadPool(READ_WORKERS, chunk_rows=64),
            )
            f.from_cpu_tensor(tfeat)
            # bit-parity first (through the page cache — bytes are the
            # point here, not latency), then defeat the cache for keeps
            ids = rng.integers(0, tn, 256)
            assert np.array_equal(np.asarray(f[ids]), tfeat[ids]), name
            f.tier_store.backing = defeat(f.tier_store.backing, device_us)
            return f

        def make_config(prefetch, mif=2):
            # split dispatch + cache_entries=0 in EVERY arm: the fused
            # path (plain features) and the embedding cache would both
            # hide exactly the tier traffic this leg measures
            return ServeConfig(
                max_batch=args.max_batch, buckets=(8, args.max_batch),
                max_delay_ms=2.0, cache_entries=0, dispatch_mode="split",
                max_in_flight=mif, record_dispatches=True,
                workload=WorkloadConfig(
                    topk=256,
                    row_topk=2 * (args.rd_hbm_rows + args.rd_host_rows),
                ),
                tier_promote_min=1.0,
                tier_promote_batch=2 * (args.rd_hbm_rows
                                        + args.rd_host_rows),
                tier_prefetch=prefetch,
                tier_prefetch_max_rows=args.rd_prefetch_rows,
            )

        def warmed_engine(feature, prefetch):
            """Engine with the r02 adaptation schedule: sketch-warm on
            the pre-shift third, fenced adapt passes until the plan is
            empty, then the placement FREEZES for the measured window
            (no background daemon — the drift is the scenario)."""
            eng = ServeEngine(model, params, make_tier_sampler(), feature,
                              make_config(prefetch))
            eng.warmup()
            eng.predict(trace[:warm_n], timeout=600)
            passes = moves = 0
            while passes < 8:
                s = eng.adapt_tiers()
                passes += 1
                moves += s["moves"]
                if s["moves"] == 0:
                    break
            if not use_direct:  # re-drop pages the warm phase pulled in
                feature.tier_store.backing.drop_cache()
            eng.reset_stats()
            return eng, passes, moves

        measured = trace[warm_n:]
        bursts = [measured[lo: lo + args.max_batch]
                  for lo in range(0, measured.size, args.max_batch)]

        def build_arm(kind, label):
            if kind == "dram":
                f = Feature(rank=0, device_cache_size=HBM_B)
                f.from_cpu_tensor(tfeat)
                eng = ServeEngine(model, params, make_tier_sampler(), f,
                                  make_config(False))
                eng.warmup()
                eng.predict(trace[:warm_n], timeout=600)
                eng.reset_stats()
                return eng
            eng, _, _ = warmed_engine(
                build_feature(f"{label}.npy", args.rd_device_us),
                prefetch=(kind == "on"),
            )
            return eng

        def run_round(tag):
            """One BURST-INTERLEAVED measured round over the post-warm
            window (the hot-set shift lands mid-window): each max-batch
            burst runs on the dram, prefetch-off, then prefetch-on arm
            back to back, so machine drift hits all three identically
            and the arms are load-matched by construction (a closed-loop
            flood would measure disk BANDWIDTH — queueing delay — where
            prefetch can only lose, since it spends reads it may waste;
            latency hiding is a below-saturation property). The ON arm
            gets the ANNOUNCE-AHEAD call after each burst — the
            flush-ahead contract (`prefetch_seeds` on the next window's
            seeds, exactly what `DistServeEngine` does per owner at
            route time), so its staging reads land during the other
            arms' service time. Latencies are exact per-burst walls (the
            latency histogram's buckets are too coarse for a 1.2x
            verdict)."""
            engs = {k: build_arm(k, f"{k}_{tag}")
                    for k in ("dram", "off", "on")}
            lats = {k: [] for k in engs}
            for j, b in enumerate(bursts):
                for k, eng in engs.items():
                    t0 = time.perf_counter()
                    eng.predict(b, timeout=600)
                    lats[k].append((time.perf_counter() - t0) * 1e3)
                    if k == "on" and j + 1 < len(bursts):
                        eng.prefetch_seeds(bursts[j + 1])
            out = {}
            for k, eng in engs.items():
                res = {
                    "p50_ms": float(np.percentile(lats[k], 50)),
                    "p99_ms": float(np.percentile(lats[k], 99)),
                    "bursts": len(bursts),
                }
                if k != "dram":
                    mix = eng.workload.skew_report()["tiers"]
                    total = sum(v["hits"] for v in mix.values()) or 1
                    res["gather_mix"] = {t: round(v["hits"] / total, 4)
                                         for t, v in mix.items()}
                    st = eng.stats
                    res["prefetch"] = {
                        "issued": st.tier_prefetch_issued,
                        "hit": st.tier_prefetch_hit,
                        "wasted": st.tier_prefetch_wasted,
                        "hit_rate": round(
                            st.tier_prefetch_hit
                            / max(st.tier_prefetch_issued, 1), 4),
                    }
                eng.stop(drain=True)
                out[k] = res
            return out

        # -- in-run BIT-PARITY: prefetch on vs off, deterministic
        # burst-sequential drive WITH announce-ahead on the on-engine
        # (the acceptance pin is logits AND dispatch log identical; the
        # device model is off here — bytes are the point, not latency)
        e_par_on = ServeEngine(model, params, make_tier_sampler(),
                               build_feature("par_on.npy"),
                               make_config(True))
        e_par_off = ServeEngine(model, params, make_tier_sampler(),
                                build_feature("par_off.npy"),
                                make_config(False))
        par_bursts = [trace[lo: lo + args.max_batch]
                      for lo in range(0, trace.size, args.max_batch)]
        rows_on, rows_off = [], []
        for j, b in enumerate(par_bursts):
            rows_on.append(e_par_on.predict(b, timeout=600))
            if j + 1 < len(par_bursts):
                e_par_on.prefetch_seeds(par_bursts[j + 1])
            rows_off.append(e_par_off.predict(b, timeout=600))
        rows_on = np.concatenate(rows_on)
        rows_off = np.concatenate(rows_off)
        assert np.array_equal(rows_on, rows_off), "prefetch changed bits!"
        log_on, log_off = e_par_on.dispatch_log, e_par_off.dispatch_log
        assert len(log_on) == len(log_off)
        for (p1, n1), (p2, n2) in zip(log_on, log_off):
            assert n1 == n2 and np.array_equal(p1, p2), \
                "prefetch changed the dispatch log!"
        parity_rows = int(rows_on.shape[0])
        parity_prefetch_hits = e_par_on.stats.tier_prefetch_hit
        assert parity_prefetch_hits > 0, "parity leg never hit staging"
        e_par_on.stop()
        e_par_off.stop()

        # -- interleaved median-of-3 (NEXT.md noise discipline), one
        # discarded warm round first (bucket compiles + first-touch) ----
        run_round("w")
        rounds = [run_round(f"r{r}") for r in range(args.repeats)]
        runs = {k: [rd[k] for rd in rounds] for k in ("dram", "off", "on")}

        def agg(kind, key):
            return median_min_max([x[key] for x in runs[kind]])

        p99 = {k: agg(k, "p99_ms") for k in runs}
        p50 = {k: agg(k, "p50_ms") for k in runs}
        p99_on_vs_off = p99["on"]["median"] / p99["off"]["median"]
        p99_on_vs_dram = p99["on"]["median"] / p99["dram"]["median"]
        hit_rates = [x["prefetch"]["hit_rate"] for x in runs["on"]]
        # diagnostics BEFORE the acceptance asserts: a failed target must
        # leave the numbers it failed on (the artifact write stays gated)
        print("REAL-DISK-DIAG "
              + json.dumps({"p99_ms": p99, "p50_ms": p50,
                            "hit_rates": hit_rates,
                            "gather_mix_on": runs["on"][-1]["gather_mix"],
                            "gather_mix_off": runs["off"][-1]["gather_mix"],
                            "prefetch_last": runs["on"][-1]["prefetch"]}),
              file=sys.stderr)
        assert p99_on_vs_off < 1.0, (
            f"prefetch-on did not beat prefetch-off on p99: "
            f"x{p99_on_vs_off:.3f}"
        )
        assert p99_on_vs_dram <= 1.2, (
            f"prefetch-on p99 is {p99_on_vs_dram:.2f}x all-DRAM "
            f"(target <= 1.2x)"
        )

        out = {
            "metric": "serve_probe_tiers_real_disk",
            "git_revision": git_revision(),
            "backend": jax.devices()[0].platform,
            "config": {
                "nodes": tn, "dim": tfeat.shape[1],
                "hbm_rows": args.rd_hbm_rows,
                "host_rows": args.rd_host_rows,
                "host_budget_bytes": HOST_B,
                "table_bytes": table_bytes,
                "capacity_ratio_vs_dram_budget": round(capacity_ratio, 2),
                "alpha": 1.3, "requests": reqs,
                "hot_set_shift_at_request": half,
                "warm_requests": warm_n,
                "max_batch": args.max_batch,
                "repeats": args.repeats, "cache_entries": 0,
                "dispatch_mode": "split",
                "drive": (
                    "burst-interleaved arms (each max-batch burst runs "
                    "dram/off/on back to back: machine drift hits all "
                    "three identically, load-matched by construction; a "
                    "closed-loop flood measures disk bandwidth — "
                    "queueing delay — not latency hiding), exact "
                    "per-burst wall latencies, announce-ahead on the ON "
                    "arm (prefetch_seeds on the next window's seeds — "
                    "the flush-ahead contract DistServeEngine implements "
                    "per owner at route time)"
                ),
                "device_model_us_per_row": args.rd_device_us,
                "device_model_note": (
                    "recorded per-row latency slept (GIL-releasing) "
                    "inside every MEASURED-ARM backing read, staging "
                    "reads included, on top of the defeated read path — "
                    "this container's backing store is hypervisor-cached "
                    "(see page_cache_defeat: the defeat is real but the "
                    "'device' answers at RAM speed), so a latency-hiding "
                    "claim needs a recorded device latency to hide; 0 "
                    "for the parity legs and defeat evidence (real path "
                    "only)"
                ),
                "read_workers": READ_WORKERS,
                "tier_prefetch_max_rows": args.rd_prefetch_rows,
            },
            "page_cache_defeat": {
                "method": method,
                "o_direct_supported": bool(use_direct),
                "memmap_warm_us_per_row": round(warm_us, 3),
                "defeated_us_per_row": round(cold_us, 3),
                "defeat_factor": round(cold_us / max(warm_us, 1e-9), 1),
                "note": (
                    "method probed empirically on the artifact dir's "
                    "filesystem. o_direct: every cold read is an aligned "
                    "pread through an O_DIRECT descriptor (page cache "
                    "bypassed entirely). fadvise fallback: pages dropped "
                    "between legs only — BEST-EFFORT (some filesystems "
                    "ignore it; the defeat_factor above is the honest "
                    "evidence either way)."
                ),
            },
            "parity": {
                "rows_checked": parity_rows,
                "dispatch_log_flushes": len(log_on),
                "prefetch_hits_during_parity": parity_prefetch_hits,
            },
            "all_dram": {"p50_ms": p50["dram"], "p99_ms": p99["dram"],
                         "runs": runs["dram"]},
            "prefetch_off": {"p50_ms": p50["off"], "p99_ms": p99["off"],
                             "runs": runs["off"]},
            "prefetch_on": {"p50_ms": p50["on"], "p99_ms": p99["on"],
                            "runs": runs["on"]},
            "prefetch_hit_rate_measured": median_min_max(hit_rates),
            "p99_on_vs_off": round(p99_on_vs_off, 4),
            "p99_on_vs_all_dram": round(p99_on_vs_dram, 4),
        }
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        return

    # -- round-14 disk-tier leg (--tiers -> TIER_r01.json) -------------------
    if args.tiers:
        import tempfile

        from quiver_tpu import Feature, QuantizedFeature
        from quiver_tpu.inference import _cached_apply, forward_logits, sample_batch
        from quiver_tpu.parallel.scaling import format_tier_markdown, tier_table
        from quiver_tpu.pipeline import AsyncReadPool
        from quiver_tpu.tiers import TIER_DISK, TIER_HBM, TIER_HOST

        # a DEDICATED graph, 10x the sweep graph: the tier claim needs
        # each flush's n_id to touch a SMALL fraction of the table (on
        # the 480-node sweep graph one flush gathers most of the graph,
        # so row access is flat and placement cannot matter). 32 small
        # communities x 150 nodes with modest degree + a [4, 4] fanout:
        # a Zipf head seed's sampled 2-hop closure is a few dozen rows
        # inside its community, so gather traffic has a row-level head
        # compact enough for the fast tiers to HOLD — the regime tier
        # placement exists for (row skew, not just seed skew).
        t_edges, tfeat, tn = community_graph(
            n_comm=32, per_comm=150, intra=6, dim=32, seed=5
        )
        ttopo = CSRTopo(edge_index=t_edges)
        T_SIZES = [4, 4]

        def make_tier_sampler():
            return GraphSageSampler(ttopo, sizes=T_SIZES, mode="TPU", seed=SEED)

        ROWB = tfeat.shape[1] * 4
        HBM_B = args.tier_hbm_rows * ROWB
        HOST_B = args.tier_host_rows * ROWB
        READ_WORKERS = 4
        tdir = tempfile.mkdtemp(prefix="qt_tiers_")
        rng = np.random.default_rng(7)
        # decorrelate the Zipf head from the stored prefix: without a
        # csr_topo reorder the static prefix is id-order, so a permuted
        # trace makes the head land anywhere — the placement-misalignment
        # every static tiering suffers when traffic drifts from ingest
        # assumptions, and exactly what the sketch-driven consumer fixes
        perm = rng.permutation(tn)
        trace = perm[zipfian_trace(tn, args.tier_requests, alpha=1.3,
                                   seed=31)].astype(np.int64)
        warm_n = len(trace) // 3
        sim_s = args.tier_disk_us_per_row * 1e-6

        def build_feature(name, adaptive):
            f = Feature(
                rank=0, device_cache_size=HBM_B, host_memory_budget=HOST_B,
                disk_path=os.path.join(tdir, name), adaptive_tiers=adaptive,
                read_pool=AsyncReadPool(READ_WORKERS, chunk_rows=128),
            )
            f.from_cpu_tensor(tfeat)
            return f

        def wrap_sim(f):
            """Add the simulated per-row cold-read latency to the disk
            tier's read_block (per chunk, so pool workers overlap the
            sleeps — modeled IO queue depth). Identical wrapper on both
            placements: the comparison isolates WHERE rows live."""
            obj = (f.tier_store.backing if f.tier_store is not None
                   else f.shard_tensor.disk_shard)
            orig = obj.read_block

            def slow(ids):
                if sim_s > 0 and ids.size:
                    time.sleep(sim_s * ids.size)
                return orig(ids)

            obj.read_block = slow

        # capacity acceptance: stored bytes >= 5x the DRAM budget
        table_bytes = tn * ROWB
        capacity_ratio = table_bytes / HOST_B
        assert capacity_ratio >= 5.0, (
            f"table {table_bytes}B is only {capacity_ratio:.1f}x the "
            f"host budget {HOST_B}B — raise n or shrink the budget"
        )

        # bit-parity acceptance: disk-tier gathers == in-DRAM gathers
        full = Feature(rank=0, device_cache_size=0)
        full.from_cpu_tensor(tfeat)
        ids = rng.integers(0, tn, 512)
        fa0 = build_feature("parity_a.npy", True)
        fs0 = build_feature("parity_s.npy", False)
        want = np.asarray(full[ids])
        assert np.array_equal(np.asarray(fa0[ids]), want), "adaptive parity"
        assert np.array_equal(np.asarray(fs0[ids]), want), "static parity"
        fq = QuantizedFeature(
            "int8", device_cache_size=8 * tn + HBM_B // 4,
            host_memory_budget=HOST_B // 4,
            disk_path=os.path.join(tdir, "q.npy"), adaptive_tiers=True,
        )
        fq.from_cpu_tensor(tfeat)
        assert np.array_equal(np.asarray(fq[ids]), fq.decode_rows(ids)), (
            "int8 disk tier not codec-exact"
        )
        parity = {"fp32_rows": int(ids.size) * 2, "int8_rows": int(ids.size)}

        # measured per-row tier costs (tier_table inputs), sim installed
        wrap_sim(fa0)
        store0 = fa0.tier_store

        def time_rows(tier, reps=5):
            res = store0.placement.residents(tier)
            batch = np.tile(res, -(-256 // max(res.size, 1)))[:256]
            np.asarray(store0.gather(batch))  # warm
            t0 = time.perf_counter()
            for _ in range(reps):
                np.asarray(store0.gather(batch))
            return (time.perf_counter() - t0) / reps / batch.size

        hbm_row_s = time_rows(TIER_HBM)
        host_row_s = time_rows(TIER_HOST)
        disk_row_s = time_rows(TIER_DISK)

        # measured per-flush device dispatch (full-DRAM forward at the
        # probe bucket — the all-HBM reference term of the cost model)
        apply = _cached_apply(model)
        ds_b = sample_batch(make_tier_sampler(), np.zeros(args.max_batch, np.int64))
        np.asarray(forward_logits(apply, params, full, ds_b))
        t0 = time.perf_counter()
        for _ in range(10):
            np.asarray(forward_logits(apply, params, full, ds_b))
        dispatch_s = (time.perf_counter() - t0) / 10

        def run_serve(adaptive, label):
            """One saturated closed-loop run. cache_entries=0: the
            embedding cache would serve the Zipf head host-side and hide
            the tier path this leg measures (cache sizing is SERVE_r06's
            question). Adaptive runs warm the sketch on the first third,
            apply fenced adapt passes until the plan is empty, then
            measure with the placement frozen."""
            f = build_feature(f"{label}.npy", adaptive)
            wrap_sim(f)
            eng = ServeEngine(
                model, params, make_tier_sampler(), f,
                ServeConfig(
                    max_batch=args.max_batch, buckets=(8, args.max_batch),
                    max_delay_ms=2.0, cache_entries=0,
                    # the row sketch must SEE at least as many rows as
                    # the fast tiers can hold, or the planner is blind
                    # to most of its own capacity
                    workload=WorkloadConfig(
                        topk=256,
                        row_topk=2 * (args.tier_hbm_rows
                                      + args.tier_host_rows),
                    ),
                    tier_promote_min=1.0,
                    tier_promote_batch=2 * (args.tier_hbm_rows
                                            + args.tier_host_rows),
                ),
            )
            eng.warmup()
            eng.predict(trace[:warm_n], timeout=600)  # sketch warm-up
            passes = moves = 0
            t_adapt0 = time.perf_counter()
            if adaptive:
                while passes < 8:
                    s = eng.adapt_tiers()
                    passes += 1
                    moves += s["moves"]
                    if s["moves"] == 0:
                        break
            adapt_wall = time.perf_counter() - t_adapt0
            promoted = eng.stats.tier_promoted  # before the stats reset
            eng.reset_stats()  # measured window only (sketches re-fill)
            chunks = np.array_split(trace[warm_n:], args.clients)
            errors = []

            def client(chunk):
                try:
                    eng.predict(chunk, timeout=600)
                except Exception as exc:
                    errors.append(repr(exc))

            t0 = time.perf_counter()
            threads = [threading.Thread(target=client, args=(c,))
                       for c in chunks]
            [t.start() for t in threads]
            [t.join() for t in threads]
            wall = time.perf_counter() - t0
            if errors:
                raise RuntimeError(f"tier clients failed ({label}): {errors}")
            tiers_mix = eng.workload.skew_report()["tiers"]
            total = sum(v["hits"] for v in tiers_mix.values()) or 1
            mix = {t: v["hits"] / total for t, v in tiers_mix.items()}
            f.tier_store.placement.check() if f.tier_store is not None else None
            return {
                "qps": (len(trace) - warm_n) / wall,
                "p99_ms": eng.stats.latency.percentile(99),
                "p50_ms": eng.stats.latency.percentile(50),
                "gather_mix": {t: round(v, 4) for t, v in mix.items()},
                "adapt_passes": passes,
                "adapt_moves": moves,
                "adapt_wall_s": round(adapt_wall, 4),
                "placement": (
                    f.tier_store.placement.counts()
                    if f.tier_store is not None else None
                ),
                "tier_promoted": promoted,
            }

        # one DISCARDED warm pair first: the first run of each arm pays
        # the bucket compiles + page-cache warm-up (measured ~4x slower
        # than steady state), which would poison an interleaved median
        # at repeats=3
        run_serve(False, "warm_s")
        run_serve(True, "warm_a")
        # interleaved median-of-3 (NEXT.md noise discipline)
        runs_s, runs_a = [], []
        for r in range(args.repeats):
            runs_s.append(run_serve(False, f"run_s{r}"))
            runs_a.append(run_serve(True, f"run_a{r}"))

        def agg(runs, key):
            return median_min_max([r[key] for r in runs])

        qps_s, qps_a = agg(runs_s, "qps"), agg(runs_a, "qps")
        p99_s, p99_a = agg(runs_s, "p99_ms"), agg(runs_a, "p99_ms")
        qps_uplift = qps_a["median"] / qps_s["median"]
        p99_ratio = p99_a["median"] / p99_s["median"] if p99_s["median"] else 1.0
        assert qps_uplift > 1.0 or p99_ratio < 1.0, (
            f"adaptive placement did not beat static: qps x{qps_uplift:.3f}, "
            f"p99 x{p99_ratio:.3f}"
        )

        # the cost model, priced with the measured inputs above
        def as_mix(run, name):
            m = run["gather_mix"]
            hbm = m.get("hbm", 0.0)
            host = m.get("host", 0.0)
            disk = max(1.0 - hbm - host, 0.0)
            return (name, hbm, host, disk)

        tt_rows = tier_table(
            mixes=[("all_hbm", 1.0, 0.0, 0.0),
                   as_mix(runs_s[-1], "static_measured"),
                   as_mix(runs_a[-1], "adaptive_measured")],
            bucket=args.max_batch, dispatch_s=dispatch_s,
            hbm_row_s=hbm_row_s, host_row_s=host_row_s,
            # the model wants the SINGLE-THREAD disk cost (it divides by
            # read_workers itself); reconstruct it from the pooled
            # measurement above
            disk_row_s=disk_row_s * READ_WORKERS,
            feature_dim=tfeat.shape[1], read_workers=READ_WORKERS,
        )
        print(format_tier_markdown(tt_rows))

        out = {
            "metric": "serve_probe_tiers",
            "git_revision": git_revision(),
            "backend": jax.devices()[0].platform,
            "config": {
                "nodes": tn, "dim": tfeat.shape[1],
                "hbm_rows": args.tier_hbm_rows,
                "host_rows": args.tier_host_rows,
                "host_budget_bytes": HOST_B,
                "table_bytes": table_bytes,
                "capacity_ratio_vs_dram_budget": round(capacity_ratio, 2),
                "alpha": 1.3, "requests": args.tier_requests,
                "max_batch": args.max_batch,
                "clients": args.clients, "repeats": args.repeats,
                "cache_entries": 0,
                "disk_us_per_row_simulated": args.tier_disk_us_per_row,
                "read_workers": READ_WORKERS,
            },
            "note": (
                "disk reads carry a SIMULATED per-row latency (labeled in "
                "config): this box's page cache makes flat-file reads "
                "DRAM-speed, production cold storage is not — the sim "
                "applies identically to both placements, so the uplift "
                "isolates WHERE rows live, which is the claim under test. "
                "cache_entries=0 so the embedding cache cannot hide the "
                "tier path. Trace hotness is PERMUTED off the stored "
                "prefix (static placement misaligned by construction — "
                "the drift scenario adaptation exists for)."
            ),
            "parity_rows_checked": parity,
            "measured_row_costs_s": {
                "hbm": hbm_row_s, "host": host_row_s,
                "disk_pooled": disk_row_s, "dispatch_s": dispatch_s,
            },
            "static": {"qps": qps_s, "p99_ms": p99_s,
                       "runs": runs_s},
            "adaptive": {"qps": qps_a, "p99_ms": p99_a,
                         "runs": runs_a},
            "adaptive_vs_static": {
                "qps_uplift_median": round(qps_uplift, 4),
                "p99_ratio_median": round(p99_ratio, 4),
            },
            "tier_table": [r._asdict() for r in tt_rows],
        }
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        return

    # -- round-13 workload-skew leg (--skew -> SERVE_r06.json) ---------------
    if args.skew:
        from quiver_tpu.parallel.scaling import skew_table

        CAP = args.skew_cache
        skew_points = []
        for alpha in (float(a) for a in args.skew_alphas.split(",")):
            trace = zipfian_trace(n, args.skew_requests, alpha=alpha, seed=29)

            # (a+b) accuracy leg: a single-host fused engine driven
            # SEQUENTIALLY (submit -> flush -> result per request), so the
            # EmbeddingCache evolves as a pure LRU — the apples-to-apples
            # measured counterpart of the sketch's Che-model prediction.
            # Threaded saturation would conflate coalescing with cache
            # behavior; the saturated cost question is the separate
            # on-vs-off leg below.
            eng = ServeEngine(
                model, params, make_full_sampler(), feat,
                ServeConfig(max_batch=8, buckets=(8,), max_delay_ms=2.0,
                            cache_entries=CAP,
                            workload=WorkloadConfig(topk=256)),
            )
            eng.warmup()
            for nid in trace:
                h = eng.submit(int(nid))
                if eng._drainable():
                    eng.flush()
                h.result(timeout=300)
            rep = eng.workload.skew_report(
                capacities=(CAP,), top_ks=(1, 8, 16, 64, 256)
            )
            measured_hit = eng.stats.cache.hit_rate
            predicted_hit = rep["predicted_hit_rate"][str(CAP)]
            # sketch top-64 vs exact counters (same count-desc/key-asc
            # tie rule on both sides)
            keys, counts = np.unique(trace, return_counts=True)
            order = np.lexsort((keys, -counts))
            exact64 = set(int(k) for k in keys[order[:64]])
            sketch64 = set(k for k, _, _ in eng.workload.topk.topk(64))
            overlap64 = len(exact64 & sketch64) / 64.0

            # (c) owner imbalance + straggler at hosts 1 and 2: the routed
            # engine's ROUTER monitor, deterministic single-threaded drive
            owner_stats = {}
            for hosts in (1, 2):
                dist = build_dist(hosts, "fused",
                                  workload=WorkloadConfig(topk=256))
                dist.predict(trace[:600])
                wr = dist.workload_report(capacities=(CAP,))
                ro = wr["router"]["owners"]
                owner_stats[str(hosts)] = {
                    "per_owner_seeds": {
                        h: v["seeds"] for h, v in ro["per_owner"].items()
                    },
                    "per_owner_lat_ms": {
                        h: {
                            "mean": round(v["lat_mean_ms"], 3),
                            "p50": round(v["lat_p50_ms"], 3),
                            "p99": round(v["lat_p99_ms"], 3),
                        }
                        for h, v in ro["per_owner"].items()
                    },
                    "imbalance": ro["imbalance"],
                    "straggler": ro["straggler"],
                }
                assert ro["imbalance"]["owners"] == hosts, ro
            point = {
                "alpha": alpha,
                "requests": args.skew_requests,
                "cache_entries": CAP,
                "distinct": int(keys.size),
                "skew": trace_skew_stats(trace),
                "top64_overlap": round(overlap64, 4),
                "measured_hit_rate": round(measured_hit, 4),
                "predicted_hit_rate": predicted_hit,
                "predicted_hit_rate_lfu_bound": (
                    rep["predicted_hit_rate_lfu_bound"][str(CAP)]
                ),
                "predicted_vs_measured_diff": round(
                    abs(predicted_hit - measured_hit), 4
                ),
                "dispatches": eng.stats.dispatches,
                "skew_report": {
                    k: rep[k]
                    for k in ("observed_events", "distinct_tracked",
                              "ticks", "top_coverage", "error_bound",
                              "cache")
                },
                "owners": owner_stats,
            }
            skew_points.append(point)
            if alpha >= 1.25:
                # the ISSUE acceptance bounds, asserted in-run at the
                # heavy-skew point
                assert overlap64 >= 0.90, (alpha, overlap64)
                assert abs(predicted_hit - measured_hit) <= 0.05, (
                    alpha, predicted_hit, measured_hit
                )

        # (d) sketch-on vs sketch-off saturated QPS, median-of-3
        # INTERLEAVED (off/on pairs back to back — same noise-honest form
        # as the round-12 journal leg): the "cheap enough to leave on"
        # claim for the sketches, measured on the threaded routed engine
        qps_skew_on, qps_skew_off = [], []
        for _ in range(3):
            _, _, w_off, _ = run_once(1.1, hosts_sweep[0], "fused", False)
            _, _, w_on, _ = run_once(
                1.1, hosts_sweep[0], "fused", False,
                workload=WorkloadConfig(topk=256),
            )
            qps_skew_off.append(round(args.requests / w_off, 1))
            qps_skew_on.append(round(args.requests / w_on, 1))
        skew_overhead_frac = 1.0 - (
            median_min_max(qps_skew_on)["median"]
            / median_min_max(qps_skew_off)["median"]
        )
        skew_ranges_overlap = (
            min(qps_skew_on) <= max(qps_skew_off)
            and min(qps_skew_off) <= max(qps_skew_on)
        )
        assert skew_overhead_frac < 0.03 or skew_ranges_overlap, (
            skew_overhead_frac, qps_skew_on, qps_skew_off
        )

        # the measured alpha-1.3 head feeds the item-3 replication table,
        # priced with the MEASURED per-owner routed-leg latency from the
        # hosts=2 run (the monitor's owner flush mean)
        heavy = max(skew_points, key=lambda p: p["alpha"])
        cov = sorted(
            (int(k), float(v))
            for k, v in heavy["skew_report"]["top_coverage"].items()
        )
        owner_lat = heavy["owners"]["2"]["per_owner_lat_ms"]
        dispatch_s = (
            sum(v["mean"] for v in owner_lat.values())
            / max(len(owner_lat), 1) / 1e3
        ) or 1e-3
        rep_rows = skew_table(
            cov, hosts=2, bucket=args.max_batch, out_dim=model.out_dim,
            dispatch_s=dispatch_s, feature_dim=feat.shape[1],
        )
        out = {
            "metric": "serve_probe_skew",
            "git_revision": git_revision(),
            "requests": args.skew_requests,
            "cache_entries": CAP,
            "max_batch": args.max_batch,
            "backend": jax.devices()[0].platform,
            "note": (
                "accuracy legs are sequential LRU-faithful drives (the "
                "predicted-vs-measured close needs the cache to be an "
                "LRU, not a coalescing race); the on-vs-off QPS leg is "
                "the threaded saturated engine, median-of-3 interleaved "
                "with min/max spreads per the noise discipline"
            ),
            "points": skew_points,
            "asserted": {
                "top64_overlap_min_at_alpha13": 0.90,
                "hit_rate_max_diff_at_alpha13": 0.05,
            },
            "sketch_overhead": {
                "qps_on": qps_skew_on,
                "qps_off": qps_skew_off,
                "frac": round(skew_overhead_frac, 4),
                "ranges_overlap": skew_ranges_overlap,
            },
            "serve_skew_overhead_frac": round(skew_overhead_frac, 4),
            "skew_table_dispatch_s": round(dispatch_s, 6),
            "skew_table_hosts2": [r._asdict() for r in rep_rows],
        }
        line = json.dumps(out)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        return

    # hosts=1 vs a plain single-host engine, bit for bit: a deterministic
    # single-threaded pass (flush composition under concurrent clients is
    # interleaving-dependent by design, so the bitwise claim is pinned on
    # the deterministic driver — the threaded runs pin parity against the
    # replay oracle instead)
    dist1 = build_dist(1, "fused")
    trace1 = zipfian_trace(n, args.requests, alpha=1.1, seed=43)
    out1 = np.asarray(dist1.predict(trace1))
    plain = ServeEngine(
        model, params, make_full_sampler(), feat,
        ServeConfig(max_batch=args.max_batch, buckets=(8, args.max_batch),
                    max_delay_ms=2.0, record_dispatches=True),
    )
    ref1 = np.asarray(plain.predict(trace1))
    assert np.array_equal(out1, ref1), (
        "hosts=1 engine diverged from the single-host engine"
    )
    hosts1_parity_rows = int(trace1.shape[0])

    # same deterministic trace WITH the lifecycle journal on: enabling
    # observability must change no served bit (the observe-only rule; the
    # engine-grain pin lives in tests/test_obs.py, this is the probe-level
    # in-run version against the same reference rows)
    dist1j = build_dist(1, "fused", journal_events=args.journal_events)
    out1j = np.asarray(dist1j.predict(trace1))
    assert np.array_equal(out1j, ref1), (
        "journal-enabled hosts=1 engine diverged — observation leaked "
        "into control flow"
    )

    points = []
    for alpha in (0.0, 1.1):
        for hosts in hosts_sweep:
            for path in ("fused", "split"):
                qps_runs, parity_rows, keep = [], 0, None
                for rep in range(args.repeats):
                    dist, trace, wall, pr = run_once(
                        alpha, hosts, path, check_parity=(rep == 0)
                    )
                    qps_runs.append(round(args.requests / wall, 1))
                    parity_rows += pr
                    if rep == 0:
                        keep = dist
                s = keep.stats
                widths = s.mean_sub_batch_width()
                router_mean = s.routed_seeds / max(s.router_dispatches, 1)
                if hosts > 1 and s.router_dispatches:
                    assert all(
                        w <= router_mean / hosts * 1.6 + 1 for w in widths.values()
                    ), (widths, router_mean, hosts)
                merged = keep.aggregate_stats()["shards_merged"]
                lat = s.latency.snapshot()
                points.append({
                    "alpha": alpha,
                    "hosts": hosts,
                    "path": path,
                    "exchange_mode": keep.exchange_mode,
                    "clients": args.clients,
                    "skew": trace_skew_stats(trace),
                    "qps": median_min_max(qps_runs),
                    "qps_runs": qps_runs,
                    "p50_ms": round(lat["p50_ms"], 3),
                    "p99_ms": round(lat["p99_ms"], 3),
                    "router_dispatches": s.router_dispatches,
                    "routed_seeds": s.routed_seeds,
                    "coalesced": s.coalesced,
                    "router_late_admitted": s.late_admitted,
                    "mean_router_flush_width": round(router_mean, 2),
                    "mean_sub_batch_width": {
                        str(h): round(w, 2) for h, w in widths.items()
                    },
                    "exchange_id_bytes": s.exchange_id_bytes,
                    "exchange_logit_bytes": s.exchange_logit_bytes,
                    "shard_edge_frac": {
                        str(h): round(st["edge_frac"], 4)
                        for h, st in keep.shard_topo_stats.items()
                    },
                    "shards_merged": {
                        k: merged[k]
                        for k in ("dispatches", "dispatch_calls",
                                  "execute_calls", "late_admitted",
                                  "dispatched_seeds", "padded_seeds",
                                  "coalesced")
                    },
                    "parity_rows_checked": parity_rows,
                })

    # saturated aggregate per (hosts, path): requests/s over the summed
    # walls across skews, from the per-repeat medians
    saturated = {}
    for hosts in hosts_sweep:
        for path in ("fused", "split"):
            ps = [p for p in points if p["hosts"] == hosts and p["path"] == path]
            wall = sum(args.requests / p["qps"]["median"] for p in ps)
            saturated[f"hosts{hosts}_{path}"] = round(
                len(ps) * args.requests / wall, 1
            )
    fused_beats_split = {
        str(h): saturated[f"hosts{h}_fused"] > saturated[f"hosts{h}_split"]
        for h in hosts_sweep
    }
    # the headline claim, restated with the NEXT.md noise discipline. At
    # hosts > 1 one-dispatch must beat two-dispatch OUTRIGHT: the split
    # path pays the per-flush feature exchange there, a structural ~5x
    # gap far above this box's noise. At hosts = 1 the two paths differ
    # by one eager dispatch per flush — a delta the 1-core box's
    # run-to-run drift exceeds in either direction (observed: the
    # saturated medians flip sign across whole probe runs), so the honest
    # per-point assert is median-wins OR overlapping per-run spreads;
    # pretending the median ordering is stable would make the artifact a
    # coin flip.
    for h in hosts_sweep:
        if h > 1:
            assert fused_beats_split[str(h)], saturated
        else:
            for alpha in (0.0, 1.1):
                pf = next(p for p in points
                          if p["hosts"] == h and p["path"] == "fused"
                          and p["alpha"] == alpha)
                ps = next(p for p in points
                          if p["hosts"] == h and p["path"] == "split"
                          and p["alpha"] == alpha)
                assert (
                    pf["qps"]["median"] > ps["qps"]["median"]
                    or (pf["qps"]["min"] <= ps["qps"]["max"]
                        and ps["qps"]["min"] <= pf["qps"]["max"])
                ), (alpha, pf["qps"], ps["qps"])

    # -- late admission under an open-loop Poisson trace ----------------------
    def run_poisson(target_qps):
        eng = ServeEngine(
            model, params, make_full_sampler(), feat,
            ServeConfig(max_batch=args.max_batch, buckets=(8, args.max_batch),
                        max_delay_ms=1.0, max_in_flight=1,
                        record_dispatches=True),
        )
        eng.warmup()
        trace = zipfian_trace(n, args.poisson_requests, alpha=0.9, seed=7)
        arrivals = poisson_arrivals(args.poisson_requests, qps=target_qps, seed=3)
        handles = []
        stop = threading.Event()

        def pump_loop():
            while not stop.is_set():
                try:
                    eng.pump()
                except Exception:
                    pass
                time.sleep(2e-4)

        # 3 pump threads against a window of 1: an age-triggered partial
        # flush blocks on the window while the device runs the previous
        # one, and arrivals during the wait ride its pad lanes
        pumps = [threading.Thread(target=pump_loop) for _ in range(3)]
        [t.start() for t in pumps]
        t0 = time.perf_counter()
        for i, nid in enumerate(trace):
            dt = arrivals[i] - (time.perf_counter() - t0)
            if dt > 0:
                time.sleep(dt)
            handles.append(eng.submit(int(nid)))
        rows = [np.asarray(h.result(timeout=300)) for h in handles]
        stop.set()
        [t.join() for t in pumps]
        while eng._drainable():
            eng.flush()
        # replay determinism: admission never perturbed the key stream
        from quiver_tpu.inference import _cached_apply, batch_logits

        apply = _cached_apply(model)
        ref_sampler = make_full_sampler()
        oracle = {}
        for padded, nvalid in eng.dispatch_log:
            logits = np.asarray(
                batch_logits(apply, params, ref_sampler, feat, padded)
            )
            for i in range(nvalid):
                oracle.setdefault(int(padded[i]), logits[i])
        for nid, row in zip(trace, rows):
            assert np.array_equal(row, oracle[int(nid)]), (
                f"POISSON PARITY VIOLATION at node {int(nid)}"
            )
        st = eng.stats
        assert st.execute_calls == st.dispatches  # fused single-host engine
        return {
            "target_qps": target_qps,
            "requests": args.poisson_requests,
            "late_admitted": st.late_admitted,
            "dispatches": st.dispatches,
            "execute_calls": st.execute_calls,
            "dispatched_seeds": st.dispatched_seeds,
            "padded_seeds": st.padded_seeds,
            "coalesced": st.coalesced,
            "parity_rows_checked": len(rows),
        }

    poisson_points = [
        run_poisson(float(q)) for q in args.poisson_qps.split(",")
    ]
    # the acceptance claim: pad slack retired real requests under Poisson
    assert sum(p["late_admitted"] for p in poisson_points) > 0, poisson_points

    # -- observability: instrumented saturated run + enabled-vs-disabled cost --
    from quiver_tpu import comm as comm_mod
    from quiver_tpu.trace import SpanRecorder

    # (a+b+c) one saturated threaded run with the journal + fleet registry
    # + comm exchange spans ON: journal-derived per-stage breakdown,
    # Perfetto timeline, Prometheus dump — parity re-asserted in-run by
    # run_once (the replay oracle does not care that the journal watched)
    obs_hosts = hosts_sweep[-1]
    comm_rec = comm_mod.record_exchange_spans(SpanRecorder())
    dist_obs, _, wall_obs, obs_parity_rows = run_once(
        1.1, obs_hosts, "fused", check_parity=True,
        journal_events=args.journal_events,
    )
    fleet = dist_obs.fleet_snapshot()
    prom_text = dist_obs.fleet_registry().to_prometheus()
    timeline_doc = dist_obs.export_chrome_trace(args.timeline or "")
    comm_mod.record_exchange_spans(None)
    rb = fleet["router"]
    assert rb["requests"] > 0 and rb["flushes"] > 0, rb
    assert any(
        fleet["per_shard"][h]["device_ms"]["n"] > 0 for h in fleet["per_shard"]
    ), fleet["per_shard"]
    assert rb["pad_frac"]["n"] == rb["flushes"], rb
    # overlap CONSISTENCY, not an overlap demand: whether two flushes
    # ever sat in flight together is a scheduling fact (the engines'
    # inflight_peak counters record it); the structural invariant is that
    # the timeline must not HIDE overlap that happened — a second flush
    # lane exists iff two flushes' assemble->resolve intervals overlapped
    lane_names = [
        e["args"]["name"]
        for e in timeline_doc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    ]
    timeline_overlapped = any(tn.startswith("flushes/") for tn in lane_names)
    ran_overlapped = dist_obs.stats.inflight_peak > 1 or any(
        e.stats.inflight_peak > 1 for e in dist_obs.engines.values()
    )
    if ran_overlapped:
        assert timeline_overlapped, (
            "in-flight overlap happened (inflight_peak > 1) but the "
            "timeline shows no second flush lane", lane_names
        )
    assert prom_text.count("# TYPE") > 20, "fleet exposition suspiciously thin"

    # (d) enabled-vs-disabled saturated QPS, median-of-3 INTERLEAVED runs
    # (off/on pairs back to back so box drift hits both sides): the
    # "cheap enough to leave on" claim, measured. Under 3% — or
    # indistinguishable from this box's run-to-run spread (ranges
    # overlap), which is the honest reading when the true delta is
    # smaller than the noise floor.
    qps_obs_on, qps_obs_off = [], []
    for _ in range(3):
        _, _, w_off, _ = run_once(1.1, hosts_sweep[0], "fused", False)
        _, _, w_on, _ = run_once(
            1.1, hosts_sweep[0], "fused", False,
            journal_events=args.journal_events,
        )
        qps_obs_off.append(round(args.requests / w_off, 1))
        qps_obs_on.append(round(args.requests / w_on, 1))
    obs_overhead_frac = 1.0 - (
        median_min_max(qps_obs_on)["median"]
        / median_min_max(qps_obs_off)["median"]
    )
    obs_ranges_overlap = (
        min(qps_obs_on) <= max(qps_obs_off)
        and min(qps_obs_off) <= max(qps_obs_on)
    )
    assert obs_overhead_frac < 0.03 or obs_ranges_overlap, (
        obs_overhead_frac, qps_obs_on, qps_obs_off
    )

    # -- measured dispatch costs: split legs, fused step, and the delta -------
    from quiver_tpu.inference import _cached_apply, time_eval_split

    apply = _cached_apply(model)
    t_sample, t_forward = time_eval_split(
        apply, params, make_full_sampler(), feat,
        np.arange(args.max_batch, dtype=np.int64), iters=20,
    )
    timer_eng = ServeEngine(
        model, params, make_full_sampler(), feat,
        ServeConfig(max_batch=args.max_batch, buckets=(args.max_batch,)),
    )
    timer_eng.warmup()
    twin = make_full_sampler()
    seeds = np.arange(args.max_batch, dtype=np.int64)
    np.asarray(timer_eng._programs(args.max_batch, params, twin.next_key(), seeds))
    t0 = time.perf_counter()
    iters = 20
    for _ in range(iters):
        out = timer_eng._programs(args.max_batch, params, twin.next_key(), seeds)
    np.asarray(out)
    t_fused = (time.perf_counter() - t0) / iters
    overhead = max((t_sample + t_forward) - t_fused, 0.0)

    tables = {}
    for dpf in (1, 2):
        pred = serve_table(
            0.0, 0.0, t_fused, ref_batch=args.max_batch,
            buckets=(8, args.max_batch), hit_rates=(0.0, 0.5),
            unique_frac=0.8, max_delay_ms=2.0, out_dim=model.out_dim,
            dispatches_per_flush=dpf, dispatch_overhead_s=overhead,
        )
        tables[str(dpf)] = {
            "rows": [p._asdict() for p in pred],
            "md": format_serve_markdown(pred),
        }

    out = {
        "metric": "serve_probe_obs",
        "git_revision": git_revision(),
        "requests": args.requests,
        "max_batch": args.max_batch,
        "repeats": args.repeats,
        "backend": jax.devices()[0].platform,
        "note": (
            "median-of-N with min/max per point: per-run numbers on this "
            "noisy 1-core box flip run to run (NEXT.md); read the medians "
            "and the spread together"
        ),
        "points": points,
        "hosts1_vs_single_host_parity_rows": hosts1_parity_rows,
        "saturated_qps": saturated,
        "fused_beats_split": fused_beats_split,
        "poisson_late_admission": poisson_points,
        "measured_sample_s": round(t_sample, 6),
        "measured_forward_s": round(t_forward, 6),
        "measured_fused_step_s": round(t_fused, 6),
        "measured_split_minus_fused_s": round(overhead, 6),
        "cost_source": "eval_split+fused_step",
        "serve_table_by_dispatches_per_flush": tables,
        "obs": {
            "journal_events": args.journal_events,
            "hosts": obs_hosts,
            "qps": round(args.requests / wall_obs, 1),
            "parity_rows_checked_with_journal_on": obs_parity_rows,
            # journal-derived per-request per-stage medians/tails + the
            # per-flush pad occupancy the QoS work will be judged by
            "router_breakdown": fleet["router"],
            "per_shard_breakdown": {
                str(h): fleet["per_shard"][h] for h in fleet["per_shard"]
            },
            "timeline_path": args.timeline,
            "timeline_events": len(timeline_doc["traceEvents"]),
            "timeline_overlapped_flush_lanes": timeline_overlapped,
            "prometheus_families": prom_text.count("# TYPE"),
            "prometheus": prom_text,
            "overhead": {
                "qps_on": qps_obs_on,
                "qps_off": qps_obs_off,
                "frac": round(obs_overhead_frac, 4),
                "ranges_overlap": obs_ranges_overlap,
            },
        },
        "serve_obs_overhead_frac": round(obs_overhead_frac, 4),
    }
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")


if __name__ == "__main__":
    main()

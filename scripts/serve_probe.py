"""Synthetic online-serving probe: QPS / tail latency / cache hit rate vs
request skew.

Replays seeded Zipfian request traces through the REAL serving engine
(`quiver_tpu.serve.ServeEngine` — micro-batching, coalescing, embedding
cache) over a small community graph, at 2-3 skew settings and two cache
sizes, and prints ONE json line (written to SERVE_r01.json by the round
driver). On this 1-core CPU box the absolute QPS is a floor, not a
ceiling — the point of the artifact is the TRAJECTORY: how hit rate,
coalescing, and dispatch count move with skew, plus the serve_table
prediction computed from the SAME measured per-batch costs so the next
round can compare model vs measurement on real hardware.

Usage: JAX_PLATFORMS=cpu python scripts/serve_probe.py [--requests 400]
       [--out SERVE_r01.json]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def community_graph(n_comm=4, per_comm=60, intra=8, seed=0):
    rng = np.random.default_rng(seed)
    n = n_comm * per_comm
    src, dst = [], []
    for u in range(n):
        cu = u // per_comm
        for v in rng.choice(per_comm, intra, replace=False) + cu * per_comm:
            src.append(u)
            dst.append(int(v))
    feat = rng.standard_normal((n, 16)).astype(np.float32)
    return np.stack([np.array(src), np.array(dst)]), feat, n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=400)
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from quiver_tpu import CSRTopo
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.parallel.scaling import format_serve_markdown, serve_table
    from quiver_tpu.pyg.sage_sampler import GraphSageSampler
    from quiver_tpu.serve import (
        ServeConfig,
        ServeEngine,
        trace_skew_stats,
        zipfian_trace,
    )

    edge_index, feat, n = community_graph()
    model = GraphSAGE(hidden_dim=32, out_dim=4, num_layers=2, dropout=0.0)

    def make_sampler():
        return GraphSageSampler(
            CSRTopo(edge_index=edge_index), sizes=[5, 5], mode="TPU", seed=1
        )

    s0 = make_sampler()
    ds0 = s0.sample_dense(np.arange(args.max_batch, dtype=np.int64))
    params = model.init(
        jax.random.key(0), jnp.zeros((ds0.n_id.shape[0], feat.shape[1])), ds0.adjs
    )

    def run(alpha, cache_entries):
        eng = ServeEngine(
            model, params, make_sampler(), feat,
            ServeConfig(max_batch=args.max_batch, max_delay_ms=2.0,
                        cache_entries=cache_entries),
        )
        trace = zipfian_trace(n, args.requests, alpha=alpha, seed=42)
        # warm EVERY bucket's compilation out of the timed window (the
        # closed-loop drain can flush at any bucket size), then reset state
        next_id = iter(range(n))
        for b in eng.config.resolved_buckets():
            for _ in range(b):
                eng.submit(next(next_id))
            eng.flush()
        eng.cache.invalidate()
        eng.reset_stats()
        t0 = time.perf_counter()
        eng.predict(trace)
        wall = time.perf_counter() - t0
        s = eng.stats
        lat = s.latency.snapshot()
        return {
            "alpha": alpha,
            "cache_entries": cache_entries,
            "skew": trace_skew_stats(trace),
            "qps": round(args.requests / wall, 1),
            "p50_ms": round(lat["p50_ms"], 3),
            "p95_ms": round(lat["p95_ms"], 3),
            "p99_ms": round(lat["p99_ms"], 3),
            "dispatches": s.dispatches,
            "dispatched_seeds": s.dispatched_seeds,
            "padded_seeds": s.padded_seeds,
            "coalesced": s.coalesced,
            "cache_hit_rate": round(s.cache.hit_rate, 4),
            "requests_per_dispatch": round(
                args.requests / max(s.dispatches, 1), 2
            ),
        }

    points = []
    for alpha in (0.0, 0.99, 1.3):
        for cache_entries in (0, 4096):
            points.append(run(alpha, cache_entries))

    # measured per-batch dispatch cost at max_batch (one warm batch_logits
    # step) -> the serve_table prediction from the same numbers
    from quiver_tpu.inference import _cached_apply, batch_logits

    apply = _cached_apply(model)
    s1 = make_sampler()
    seeds = np.arange(args.max_batch, dtype=np.int64)
    np.asarray(batch_logits(apply, params, s1, feat, seeds))  # warm
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        out = batch_logits(apply, params, s1, feat, seeds)
    jax.block_until_ready(out)
    t_dispatch = (time.perf_counter() - t0) / iters
    # the probe cannot split sample/gather/forward without perturbing the
    # measurement; report the fused cost in the sample slot (the table sums
    # the three legs, so the prediction is unchanged)
    pred = serve_table(
        t_dispatch, 0.0, 0.0, ref_batch=args.max_batch,
        buckets=(args.max_batch,), hit_rates=(0.0, 0.5, 0.9),
        unique_frac=0.8, max_delay_ms=2.0,
    )

    out = {
        "metric": "serve_probe",
        "requests": args.requests,
        "max_batch": args.max_batch,
        "backend": jax.devices()[0].platform,
        "points": points,
        "measured_dispatch_s": round(t_dispatch, 6),
        "serve_table": [p._asdict() for p in pred],
        "serve_table_md": format_serve_markdown(pred),
    }
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(line + "\n")


if __name__ == "__main__":
    main()

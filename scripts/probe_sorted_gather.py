"""Probe: does position LOCALITY change the element-gather rate? (honest
windows — the round-3 'sort order is irrelevant' conclusion was measured
under the RPC floor). If sorted positions gather meaningfully faster, a
cheap sort (~0.5 ms/M) in front of the 1.07M-element neighbor fetch
(~11 ms) would pay."""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench

bench.enable_compile_cache()

import jax
import jax.numpy as jnp
from jax import lax

ITERS = 200
W = 1_048_576


def main():
    _, indices_np = bench.build_graph()
    tab = jax.device_put(jnp.asarray(indices_np.astype(np.int32)))
    int(tab[-1])
    E = tab.shape[0]
    rng = np.random.default_rng(0)
    raw = rng.integers(0, E, W)
    variants = {
        "random": raw,
        "sorted": np.sort(raw),
        # blockwise-sorted: sort within 8k-position chunks — what an in-jit
        # pre-sort of each hop's row-major frontier would roughly give
        "block-sorted": np.sort(raw.reshape(-1, 8192), axis=1).reshape(-1),
    }
    floor = bench.measure_rpc_floor()

    @jax.jit
    def run(tab, idx):
        def body(acc, i):
            sh = (idx + i) % E  # +i keeps iterations distinct, order intact
            return acc + jnp.take(tab, sh).sum(dtype=jnp.int32), None

        acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(ITERS, dtype=jnp.int32))
        return acc

    for name, ids in variants.items():
        idx = jax.device_put(jnp.asarray(ids.astype(np.int32)))
        int(run(tab, idx))
        t0 = time.time()
        int(run(tab, idx))
        dt = time.time() - t0 - floor
        print(f"  {name:12s}: {ITERS*W/dt/1e6:7.1f}M elems/s ({dt/ITERS*1e3:.2f} ms/iter)")


if __name__ == "__main__":
    main()

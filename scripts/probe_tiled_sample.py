"""Probe 4: tile-fetch neighbor sampling vs element-gather sampling.

probe_rowgather_width measured 128-wide int32 row gathers at ~145M
rows/s (~74 GB/s — bandwidth regime) vs 45-80M desc/s for one-element
gathers, and one-hot lane select nearly free vs take_along_axis. Design
under test: store edges in a [M, 128] tile table with every node's edge
list starting 128-aligned (block_base[i]); a sampled position p of node
i lives at tile row block_base[i] + p//128, lane p%128. The neighbor
fetch becomes ONE row gather per sampled lane (128 elems ride the
descriptor) + an in-register one-hot select — exact for EVERY degree,
no copy-all/hub split at all.

Checks bit-equality vs the flat path (same Fisher-Yates positions ->
same neighbors) and times both at the e2e hop shapes.

Run: python -u scripts/probe_tiled_sample.py   (TPU, nothing concurrent)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def measure_rpc_floor(dev_x, n=6):
    ts = []
    for _ in range(n):
        t0 = time.time()
        float(jnp.sum(dev_x[:8]))
        ts.append(time.time() - t0)
    return float(np.median(ts))


LANE = 128


def build_tiled(indptr_np, indices_np):
    deg = np.diff(indptr_np)
    rows_per = np.maximum(-(-deg // LANE), 0)  # ceil(deg/LANE); 0-deg -> 0
    base = np.zeros(len(deg) + 1, np.int64)
    np.cumsum(rows_per, out=base[1:])
    M = int(base[-1])
    tiles = np.zeros((M, LANE), np.int32)
    # vectorized fill: flat position of edge j of node i = base[i]*LANE + j
    out_pos = (
        np.repeat(base[:-1] * LANE, deg)
        + np.arange(len(indices_np))
        - np.repeat(indptr_np[:-1], deg)
    )
    tiles.reshape(-1)[out_pos] = indices_np
    return tiles, base[:-1].astype(np.int64), deg.astype(np.int32)


def fy_positions(key, deg, k):
    from quiver_tpu.ops.sample import fisher_yates_positions

    return fisher_yates_positions(key, deg, k)


def main():
    from bench import build_graph
    from quiver_tpu.ops.sample import sample_layer

    indptr_np, indices_np = build_graph()
    print("building tiled layout...", flush=True)
    t0 = time.time()
    tiles_np, base_np, deg_np = build_tiled(indptr_np, indices_np)
    print(
        f"tiled: M={tiles_np.shape[0]} rows ({tiles_np.nbytes/1e9:.2f} GB vs "
        f"flat {indices_np.nbytes*4/1e9 if indices_np.dtype==np.int64 else indices_np.astype(np.int32).nbytes/1e9:.2f} GB), "
        f"built in {time.time()-t0:.1f}s",
        flush=True,
    )

    indptr = jnp.asarray(indptr_np)
    indices = jnp.asarray(indices_np.astype(np.int32))
    tiles = jnp.asarray(tiles_np)
    # combo per-node table: (block_base, deg) — one dim-2 row gather serves both
    bd = jnp.stack(
        [base_np.astype(np.int32), deg_np.astype(np.int32)], axis=1
    )
    tiles.block_until_ready()
    floor = measure_rpc_floor(indices)
    print(f"rpc floor {floor:.3f}s", flush=True)

    def tiled_sample_layer(bd_tab, tile_tab, seeds, seed_valid, k, key):
        n = bd_tab.shape[0]
        s = jnp.clip(seeds, 0, n - 1).astype(jnp.int32)
        both = jnp.take(bd_tab, s, axis=0)
        base, deg = both[:, 0], both[:, 1]
        deg = jnp.where(seed_valid, deg, 0)
        pos, valid = fy_positions(key, deg, k)
        rows = base[:, None] + lax.shift_right_logical(pos, 7)
        rows = jnp.clip(rows, 0, tile_tab.shape[0] - 1)
        lane = jnp.bitwise_and(pos, LANE - 1)
        win = jnp.take(tile_tab, rows, axis=0)  # [B, k, LANE]
        oh = lane[:, :, None] == jnp.arange(LANE, dtype=jnp.int32)[None, None, :]
        nbrs = jnp.where(oh, win, 0).sum(axis=2).astype(tile_tab.dtype)
        return nbrs, valid

    # --- bit-equality vs flat path (same key -> same FY positions) -------
    rng = np.random.default_rng(1)
    seeds = jnp.asarray(rng.integers(0, len(deg_np), 4096).astype(np.int32))
    sv = jnp.ones((4096,), bool)
    key = jax.random.key(42)
    for k in (5, 10, 15):
        a, va = sample_layer(indptr, indices, seeds, sv, k, key)
        b, vb = jax.jit(tiled_sample_layer, static_argnames=("k",))(
            bd, tiles, seeds, sv, k=k, key=key
        )
        a, va, b, vb = map(np.asarray, (a, va, b, vb))
        assert (va == vb).all()
        assert (a[va] == b[vb]).all(), f"k={k} mismatch"
        print(f"bit-equality k={k}: OK ({int(va.sum())} valid draws)", flush=True)

    # --- timing at e2e hop shapes ----------------------------------------
    ITERS = 100

    def timed(run, args, label):
        t0 = time.time()
        out = int(np.asarray(run(*args, jax.random.key(5)))[0])
        compile_s = time.time() - t0
        t0 = time.time()
        out = int(np.asarray(run(*args, jax.random.key(6)))[0])
        dt = max(time.time() - t0 - floor, 1e-9)
        print(
            f"{label:34s}: {dt*1e3/ITERS:7.2f} ms/iter  "
            f"(compile+first {compile_s:.1f}s, chk {out & 0xffff})",
            flush=True,
        )

    for B, k in ((135_168, 5), (180_224, 5), (16_384, 10), (1024, 15)):
        def make_flat(B=B, k=k):
            @jax.jit
            def run(ip, ix, key0):
                def body(acc, i):
                    kk = jax.random.fold_in(key0, i)
                    cur = jax.random.randint(kk, (B,), 0, ip.shape[0] - 1, jnp.int32)
                    nbrs, valid = sample_layer(ip, ix, cur, jnp.ones((B,), bool), k, kk)
                    return acc + nbrs.sum(dtype=jnp.int32) + valid.sum(dtype=jnp.int32), None

                acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(ITERS, dtype=jnp.int32))
                return jnp.stack([acc])

            return run

        def make_tiled(B=B, k=k):
            @jax.jit
            def run(bd_tab, tile_tab, key0):
                def body(acc, i):
                    kk = jax.random.fold_in(key0, i)
                    cur = jax.random.randint(kk, (B,), 0, bd_tab.shape[0] - 1, jnp.int32)
                    nbrs, valid = tiled_sample_layer(
                        bd_tab, tile_tab, cur, jnp.ones((B,), bool), k, kk
                    )
                    return acc + nbrs.sum(dtype=jnp.int32) + valid.sum(dtype=jnp.int32), None

                acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(ITERS, dtype=jnp.int32))
                return jnp.stack([acc])

            return run

        timed(make_flat(), (indptr, indices), f"flat  sample_layer ({B},{k})")
        timed(make_tiled(), (bd, tiles), f"tiled sample_layer ({B},{k})")


if __name__ == "__main__":
    main()

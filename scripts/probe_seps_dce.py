"""Confirm the SEPS-bench DCE hazard: consuming only adj.mask lets XLA
delete the neighbor-id gathers (masks depend only on degrees), so the
benched program is not doing the sampling it claims. Compare mask-only vs
mask+n_id consumption on the same scanned fused sampler."""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench

bench.enable_compile_cache()

import jax
import jax.numpy as jnp
from jax import lax

from quiver_tpu.pyg.sage_sampler import sample_dense_fused

ITERS = 100
SIZES = (15, 10, 5)


def main():
    indptr_np, indices_np = bench.build_graph()
    indptr = jax.device_put(jnp.asarray(indptr_np.astype(np.int32)))
    indices = jax.device_put(jnp.asarray(indices_np.astype(np.int32)))
    int(indptr[-1]), int(indices[-1])
    rng = np.random.default_rng(1)
    seeds = jax.device_put(
        jnp.asarray(rng.integers(0, indptr.shape[0] - 1, (24, 1024)).astype(np.int32))
    )
    floor = bench.measure_rpc_floor()

    def make(consume):
        @jax.jit
        def run(ip, ix, key0, seeds_all):
            m = seeds_all.shape[0]

            def body(acc, i):
                key = jax.random.fold_in(key0, i)
                ds = sample_dense_fused(ip, ix, key, seeds_all[i % m], SIZES)
                v = sum(a.mask.sum(dtype=jnp.int32) for a in ds.adjs)
                if consume == "mask+nid":
                    v = v + (ds.n_id.sum(dtype=jnp.int32) & 1)
                return acc + v, None

            acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(ITERS, dtype=jnp.int32))
            return acc

        return run

    for consume in ("mask_only", "mask+nid"):
        run = make(consume)
        int(run(indptr, indices, jax.random.key(0), seeds))
        t0 = time.time()
        int(run(indptr, indices, jax.random.key(1), seeds))
        dt = time.time() - t0 - floor
        print(f"  {consume:10s}: {dt/ITERS*1e3:6.2f} ms/iter")


if __name__ == "__main__":
    main()

"""Synthetic accuracy + byte probe for the quantized feature store.

Trains the hermetic community-graph task twice through the REAL tiered
prefetch pipeline — fp32 `Feature` vs int8 `QuantizedFeature` (same
sampler seed, same init, same HBM byte budget) — and reports whether the
int8 loss curve tracks fp32 within tolerance, plus the measured wire
bytes each run actually staged and a fused dequant-gather rate on the
current backend. This is the runnable form of
tests/test_quant.py::test_int8_e2e_matches_fp32_loss_curve; on a real
TPU, bench.py's `quant_int8_*` context rows carry the hardware rates.

Usage: JAX_PLATFORMS=cpu python scripts/quant_probe.py [--steps 12]
Prints ONE json line.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def community_graph(n_comm=4, per_comm=40, intra=6, seed=0):
    rng = np.random.default_rng(seed)
    n = n_comm * per_comm
    src, dst = [], []
    for u in range(n):
        cu = u // per_comm
        for v in rng.choice(per_comm, intra, replace=False) + cu * per_comm:
            src.append(u)
            dst.append(int(v))
    feat = rng.standard_normal((n, 16)).astype(np.float32)
    labels = (np.arange(n) // per_comm).astype(np.int32)
    return np.stack([np.array(src), np.array(dst)]), feat, labels, n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--tol", type=float, default=0.25)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import optax

    from quiver_tpu import CSRTopo, Feature, QuantizedFeature
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.pipeline import (
        TieredFeaturePipeline,
        TrainPipeline,
        make_tiered_train_step,
    )
    from quiver_tpu.pyg.sage_sampler import GraphSageSampler
    from quiver_tpu.quant import get_codec, make_quantized_train_step
    from quiver_tpu.trace import gbps

    edge_index, feat, labels, n = community_graph()
    dim = feat.shape[1]
    rng = np.random.default_rng(0)
    batches = [rng.integers(0, n, 32).astype(np.int64) for _ in range(args.steps)]
    lab = jnp.asarray(labels)
    budget_rows = n // 2
    c8 = get_codec("int8")

    def run(feature, step_maker):
        topo = CSRTopo(edge_index=edge_index)
        sampler = GraphSageSampler(topo, sizes=[5, 5], mode="TPU", seed=1)
        model = GraphSAGE(hidden_dim=32, out_dim=4, num_layers=2, dropout=0.0)
        tx = optax.adam(5e-3)
        pipe = TieredFeaturePipeline(feature)
        step_fn = step_maker(model, tx, pipe)
        ds0 = sampler.sample_dense(batches[0])
        x0 = jnp.zeros((ds0.n_id.shape[0], dim), jnp.float32)
        params = model.init(jax.random.key(0), x0, ds0.adjs)
        opt_state = tx.init(params)
        tp = TrainPipeline(sampler, feature, step_fn, tiered=pipe)
        _, _, losses = tp.run_epoch(batches, params, opt_state, jax.random.key(1))
        # wire bytes actually staged: cold rows x D x stored element width
        wire = tp.stats.cold_rows * dim * int(np.dtype(feature.dtype).itemsize)
        return np.asarray(losses), tp.stats.cold_rows, wire

    f32 = Feature(rank=0, device_list=[0], device_cache_size=budget_rows * dim * 4)
    f32.from_cpu_tensor(feat)
    losses_f, cold_f, wire_f = run(
        f32, lambda m, tx, p: make_tiered_train_step(m, tx, lab, p.hot_table)
    )

    q8 = QuantizedFeature(
        "int8", rank=0,
        # full-N side tables are charged at ingest; this buys exactly
        # budget_rows of hot int8 payload (same hot set as the fp32 run)
        device_cache_size=int(n * c8.side_bytes_per_row + budget_rows * dim),
    )
    q8.from_cpu_tensor(feat)
    losses_q, cold_q, wire_q = run(
        q8,
        lambda m, tx, p: make_quantized_train_step(
            m, tx, lab, p.hot_table, q8.scale, q8.zero, codec="int8"
        ),
    )

    # fused dequant-gather rate on THIS backend (CPU mesh unless run on TPU):
    # wire-true bytes via trace.gbps(bytes_per_elem=codec)
    from quiver_tpu.quant import gather_dequant

    enc = c8.encode(feat)
    payload = jnp.asarray(enc.payload)
    scale, zero = jnp.asarray(enc.scale), jnp.asarray(enc.zero)
    ids = jnp.asarray(rng.integers(0, n, 4096).astype(np.int32))
    g = jax.jit(lambda p, i, s, z: gather_dequant(c8, p, i, s, z))
    np.asarray(g(payload, ids, scale, zero))  # compile + warm
    iters = 50
    t0 = time.time()
    acc = None
    for _ in range(iters):
        acc = g(payload, ids, scale, zero)
    jax.block_until_ready(acc)
    dt = time.time() - t0
    rate_wire = gbps(iters * ids.shape[0], dim, dt, bytes_per_elem=c8.bytes_per_elem)

    diff = np.abs(losses_q - losses_f)
    out = {
        "metric": "quant_int8_vs_fp32_probe",
        "steps": args.steps,
        "loss_fp32": [round(float(x), 5) for x in losses_f],
        "loss_int8": [round(float(x), 5) for x in losses_q],
        "max_abs_loss_diff": round(float(diff.max()), 5),
        "final4_mean_diff": round(
            float(abs(losses_q[-4:].mean() - losses_f[-4:].mean())), 5
        ),
        "within_tol": bool(diff.max() < args.tol),
        "int8_learns": bool(losses_q[-4:].mean() < losses_q[:4].mean()),
        "cold_rows": {"fp32": int(cold_f), "int8": int(cold_q)},
        "cold_wire_bytes": {"fp32": int(wire_f), "int8": int(wire_q)},
        "hot_capacity_multiplier": round(c8.capacity_multiplier(dim), 3),
        "gather_gbps_wire_int8": round(rate_wire, 4),
        "backend": jax.devices()[0].platform,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""Probe 7: does the 128-lane gather cliff apply to f32 FEATURE rows?

probe_rowgather_width found int32 row gathers jump from 28M rows/s
(L=32) to 145M rows/s (L=128) — the native-lane tile width. The feature
table is [N, 100] f32 (~94M rows/s, r4 correction). If a [N, 128]-padded
f32 table gathers at the L=128 rate, the e2e feature fetch (~1.1M rows/
step) gets ~1.5x faster for 28% more HBM; the model then consumes
x[:, :100] (one cheap contiguous slice).

Measures [B]-row gathers from [N, D] f32 at D in {100, 112, 120, 128},
plus gather+slice-to-100 at D=128.

Run: python -u scripts/probe_feature_pad128.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

N = 2_449_029
B = 262_144
ITERS = 120


def main():
    table128 = jax.jit(
        lambda k: jax.random.normal(k, (N, 128), jnp.float32)
    )(jax.random.key(7))
    table128.block_until_ready()
    ts = []
    for _ in range(6):
        t0 = time.time()
        float(jnp.sum(table128[0, :8]))
        ts.append(time.time() - t0)
    floor = float(np.median(ts))
    print(f"rpc floor {floor:.3f}s", flush=True)

    def timed(run, args, label, rows_per_iter=B):
        t0 = time.time()
        out = float(np.asarray(run(*args, jax.random.key(5)))[0])
        compile_s = time.time() - t0
        t0 = time.time()
        out = float(np.asarray(run(*args, jax.random.key(6)))[0])
        dt = max(time.time() - t0 - floor, 1e-9)
        rate = rows_per_iter * ITERS / dt
        print(
            f"{label:28s}: {dt*1e3/ITERS:7.2f} ms/iter  {rate/1e6:7.1f}M rows/s  "
            f"(compile+first {compile_s:.1f}s)",
            flush=True,
        )

    def make(D, slice_to=None):
        @jax.jit
        def run(tab, key0):
            t = tab[:, :D]

            def body(acc, i):
                kk = jax.random.fold_in(key0, i)
                ids = jax.random.randint(kk, (B,), 0, N, jnp.int32)
                got = jnp.take(t, ids, axis=0)
                if slice_to is not None:
                    got = got[:, :slice_to]
                return acc + got.sum(dtype=jnp.float32), None

            acc, _ = lax.scan(body, jnp.float32(0), jnp.arange(ITERS, dtype=jnp.int32))
            return jnp.stack([acc])

        return run

    for D in (100, 112, 120, 127, 128):
        timed(make(D), (table128,), f"gather [N,{D}] f32")
    timed(make(128, slice_to=100), (table128,), "gather [N,128] -> [:,:100]")


if __name__ == "__main__":
    main()

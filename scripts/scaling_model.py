"""Emit the predicted multi-chip scaling table (SCALING.md + one JSON line).

Usage:
    python scripts/scaling_model.py [--step-ms 55] [--bench BENCH.json]
        [--ici-gbps 90] [--dcn-gbps 25] [--out SCALING.md]

Single-chip step time comes from --step-ms, or is pulled from a bench
artifact's e2e context (fused epoch / 193 steps) with --bench. See
quiver_tpu/parallel/scaling.py for the model and its assumptions; the
reference's measured counterpart is docs/Introduction_en.md:144-158."""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--step-ms", type=float, default=None)
    ap.add_argument("--bench", default=None, help="BENCH_r*.json to read e2e from")
    ap.add_argument("--ici-gbps", type=float, default=90.0)
    ap.add_argument("--dcn-gbps", type=float, default=25.0)
    ap.add_argument("--steps-per-epoch", type=int, default=193)
    # eval-shaped serve dispatch cost (NEXT.md follow-up b): sample +
    # forward at --serve-ref-batch, measured by bench.py's serve section
    # (context serve_sample_s/serve_forward_s) or passed directly. When
    # present, serve_table prices QPS from THESE instead of the
    # pessimistic train-step bound.
    ap.add_argument("--serve-sample-ms", type=float, default=None)
    ap.add_argument("--serve-forward-ms", type=float, default=None)
    ap.add_argument("--serve-ref-batch", type=int, default=64)
    # one-vs-two-dispatch model (round 11): fixed per-execute overhead —
    # the RPC/launch floor paid once per flush on the fused serve path,
    # twice on the split path. Measured by bench.py's serve section as
    # serve_split_minus_fused_s (picked up via --bench) or passed here.
    ap.add_argument("--serve-overhead-ms", type=float, default=None)
    # distributed serving (round 10): H-host rows for the seed-ownership
    # routed engine — per-shard dispatch + DCN exchange term
    ap.add_argument("--serve-hosts", default="1,2,4,8")
    ap.add_argument("--serve-out-dim", type=int, default=47)
    # hot-shard replication what-if (round 13): head-concentration curve
    # source — a SERVE_r06 skew artifact's measured top_coverage, or an
    # analytic Zipf(alpha) curve when no artifact is given
    ap.add_argument("--tier", default=None,
                    help="TIER_r01.json tiers artifact to read measured "
                         "row costs + hit mixes from (default: analytic "
                         "placeholder costs, labeled)")
    # round-18 flush-ahead prefetch pricing: the measured fraction of
    # disk rows already staged in DRAM when the gather runs
    ap.add_argument("--tier-prefetch", default=None,
                    help="flush-ahead prefetch hit rate for the tier "
                         "table: a fraction in [0,1], or a TIER_r02.json "
                         "real-disk artifact to read the measured "
                         "median hit rate from (default: 0, labeled)")
    ap.add_argument("--skew", default=None,
                    help="SERVE_r06.json skew artifact to read the "
                         "measured head-concentration curve from")
    ap.add_argument("--skew-alpha", type=float, default=1.3,
                    help="analytic Zipf alpha for the replication table "
                         "when no --skew artifact is given")
    ap.add_argument("--skew-nodes", type=int, default=100_000)
    # round-17 streaming-graph ingest pricing (delta_table): measured
    # per-edge append + per-commit swap costs from bench.py's stream leg
    # (context stream_append_s / stream_swap_s, picked up via --bench)
    # or passed directly
    ap.add_argument("--stream-append-us", type=float, default=None,
                    help="host pad-lane apply cost per edge (us; bench "
                         "stream_append_s)")
    ap.add_argument("--stream-swap-ms", type=float, default=None,
                    help="batched device tile-swap cost per commit (ms; "
                         "bench stream_swap_s)")
    ap.add_argument("--stream-commit-s", type=float, default=1.0,
                    help="commit period for the ingest table")
    # round-21 graph lifecycle pricing: steady-state churn (deletes/TTL
    # expiry lane rewrites) + amortized background compaction on top of
    # the round-17 ingest table (delta_table's lifecycle kwargs)
    ap.add_argument("--lifecycle", action="store_true",
                    help="emit the round-21 lifecycle section (ingest "
                         "table re-priced with churn + compaction terms)")
    ap.add_argument("--stream-delete-us", type=float, default=None,
                    help="lane-rewrite cost per deleted/expired edge "
                         "(us; bench stream_delete_s)")
    ap.add_argument("--stream-compact-ms", type=float, default=None,
                    help="one background compaction pass (ms; bench "
                         "stream_compact_s)")
    ap.add_argument("--delete-frac", type=float, default=1.0,
                    help="deletions+expiries per appended edge at steady "
                         "state (1.0 = flat footprint: every append "
                         "eventually expires)")
    ap.add_argument("--compact-every-commits", type=float, default=10.0,
                    help="commits between background compaction passes")
    # round-24 zero-stall commit pricing: the drain-vs-flip comparison.
    # The stall input is MEASURED by serve_probe --stream-stall
    # (STREAM_r02.json commit_stall_us, the _seq flip hold)
    ap.add_argument("--stream-commit-stall-us", type=float, default=None,
                    help="measured zero-stall per-commit flip hold (us; "
                         "serve_probe --stream-stall commit_stall_us)")
    ap.add_argument("--fence-mode", choices=("fenced", "zerostall"),
                    default="zerostall",
                    help="commit discipline for the round-24 stall "
                         "re-pricing under --lifecycle")
    # round-19 link-prediction pricing (lp_table): measured fused
    # temporal step + per-pair head costs from bench.py's workloads leg
    # (context temporal_step_s / lp_head_s, picked up via --bench)
    ap.add_argument("--lp-step-ms", type=float, default=None,
                    help="fused temporal serve-step cost at --lp-ref-batch "
                         "(ms; bench temporal_step_s)")
    ap.add_argument("--lp-ref-batch", type=int, default=64)
    ap.add_argument("--lp-head-us", type=float, default=None,
                    help="pair scoring-head cost per pair (us; bench "
                         "lp_head_s)")
    # round 20: host-side admission cost. serve_table caps every QPS row
    # at the serial submit-path rate 1e6/host_submit_us when > 0.
    # round 22: FRONTEND_r02.json also carries host_resolve_us (the drain
    # half); the cap becomes 1e6/(host_submit_us + host_resolve_us).
    # round 23: FRONTEND_r03.json carries owner_fanout / leg_merge_us —
    # the host-mode routed-dispatch pricing inputs (concurrent owner
    # fan-out: max(legs) + merge instead of sum(legs)). --frontend takes
    # a comma-separated list so r02 (admission/drain) and r03 (fan-out)
    # artifacts can both feed one table.
    ap.add_argument("--frontend", default=None,
                    help="host submit cost: a float (us/request) or "
                         "comma-separated FRONTEND_r0*.json paths — "
                         "FRONTEND_r02.json contributes host_submit_us/"
                         "host_resolve_us, FRONTEND_r03.json contributes "
                         "owner_fanout/leg_merge_us (all measured by "
                         "scripts/bench_frontend.py)")
    ap.add_argument("--out", default=None, help="write a markdown table here")
    args = ap.parse_args()

    host_submit_us = 0.0
    host_resolve_us = 0.0
    owner_fanout = None
    leg_merge_us = 0.0
    fanout_source = None
    host_submit_source = (
        "none (analytic: no host admission cap — pass --frontend)"
    )
    if args.frontend:
        for token in args.frontend.split(","):
            token = token.strip()
            if not token:
                continue
            try:
                host_submit_us = float(token)
                host_submit_source = f"--frontend {host_submit_us}"
                continue
            except ValueError:
                pass
            with open(token) as fh:
                fr = json.load(fh)
            if "host_submit_us" in fr:
                host_submit_us = float(fr["host_submit_us"])
                host_resolve_us = float(fr.get("host_resolve_us", 0.0))
                host_submit_source = (
                    f"{token} host_submit_us (measured, "
                    "scripts/bench_frontend.py)"
                )
                if host_resolve_us:
                    host_submit_source = (
                        f"{token} host_submit_us+host_resolve_us "
                        "(measured, scripts/bench_frontend.py)"
                    )
            # round-23 r03 keys: routed-dispatch fan-out pricing
            if "owner_fanout" in fr:
                owner_fanout = int(fr["owner_fanout"])
                leg_merge_us = float(fr.get("leg_merge_us", 0.0))
                fanout_source = (
                    f"{token} owner_fanout/leg_merge_us (measured, "
                    "scripts/bench_frontend.py --r03)"
                )

    step_s = (args.step_ms or 0) / 1e3
    source = f"--step-ms {args.step_ms}"
    serve_sample_s = (args.serve_sample_ms or 0) / 1e3
    serve_forward_s = (args.serve_forward_ms or 0) / 1e3
    serve_overhead_s = (args.serve_overhead_ms or 0) / 1e3
    serve_ref_batch = args.serve_ref_batch
    serve_source = "--serve-sample-ms/--serve-forward-ms"
    if args.bench:
        with open(args.bench) as fh:
            data = json.load(fh)
        ctx = (data.get("parsed") or data).get("context", {})
        if not step_s:
            epoch = ctx.get("e2e_fused_epoch_s")
            if epoch:
                step_s = epoch / args.steps_per_epoch
                source = f"{args.bench} e2e_fused_epoch_s={epoch}"
        if not (serve_sample_s or serve_forward_s):
            if ctx.get("serve_sample_s") or ctx.get("serve_forward_s"):
                serve_sample_s = ctx.get("serve_sample_s", 0.0)
                serve_forward_s = ctx.get("serve_forward_s", 0.0)
                serve_ref_batch = ctx.get("serve_eval_ref_batch", serve_ref_batch)
                serve_source = f"{args.bench} serve_sample_s/serve_forward_s"
        if args.serve_overhead_ms is None and ctx.get("serve_split_minus_fused_s"):
            serve_overhead_s = ctx["serve_split_minus_fused_s"]
        if (args.stream_append_us is None
                and ctx.get("stream_append_s") is not None):
            args.stream_append_us = ctx["stream_append_s"] * 1e6
        if (args.stream_swap_ms is None
                and ctx.get("stream_swap_s") is not None):
            args.stream_swap_ms = ctx["stream_swap_s"] * 1e3
        if (args.stream_delete_us is None
                and ctx.get("stream_delete_s") is not None):
            args.stream_delete_us = ctx["stream_delete_s"] * 1e6
        if (args.stream_compact_ms is None
                and ctx.get("stream_compact_s") is not None):
            args.stream_compact_ms = ctx["stream_compact_s"] * 1e3
        if args.lp_step_ms is None and ctx.get("temporal_step_s") is not None:
            args.lp_step_ms = ctx["temporal_step_s"] * 1e3
        if args.lp_head_us is None and ctx.get("lp_head_s") is not None:
            args.lp_head_us = ctx["lp_head_s"] * 1e6
        if not host_submit_us and ctx.get("host_submit_us"):
            host_submit_us = float(ctx["host_submit_us"])
            host_resolve_us = float(ctx.get("host_resolve_us", 0.0))
            host_submit_source = (
                f"{args.bench} context host_submit_us (measured, "
                "bench.py serve)"
            )
    if not step_s:
        step_s = 0.0415  # PERF_NOTES.md round-4 measured products step (fused, floor-corrected)
        source = "PERF_NOTES.md round-4 default 41.5 ms"

    from quiver_tpu.parallel.scaling import (
        ShapeMesh,
        delta_table,
        format_delta_markdown,
        format_fetch_markdown,
        format_lp_markdown,
        format_markdown,
        format_quant_markdown,
        format_serve_markdown,
        format_skew_markdown,
        format_tier_markdown,
        lp_table,
        products_scaling_table,
        quant_fetch_table,
        serve_table,
        sharded_fetch_table,
        skew_table,
        tier_table,
    )

    bw = {"ici_bytes_per_s": args.ici_gbps * 1e9, "dcn_bytes_per_s": args.dcn_gbps * 1e9}
    rows = products_scaling_table(
        step_s, steps_per_epoch_1chip=args.steps_per_epoch, bandwidths=bw
    )
    md = format_markdown(rows, step_s, bw)
    # flat-vs-tiled shard-LOCAL fetch term at the products config on the
    # 2-host sharded-topology mesh (collective bytes are layout-invariant;
    # this per-chip HBM term is where the layouts differ)
    fetch_mesh = ShapeMesh(
        ("host", "dp", "ici"), {"host": 2, "dp": 2, "ici": 2}
    )
    fetch_rows = sharded_fetch_table(fetch_mesh, (15, 10, 5), 1024)
    fetch_md = (
        "## Sharded-topology shard-local fetch: flat vs tiled "
        "(host=2,dp=2,ici=2, products config)\n\n"
        + format_fetch_markdown(fetch_rows)
    )
    # per-codec quantized feature-store rows (quiver_tpu.quant): hot-cache
    # capacity multiplier + gather/H2D byte reduction at the products config
    quant_rows = quant_fetch_table((15, 10, 5), 1024, 100)
    quant_md = (
        "## Quantized feature store: per-codec capacity / byte table "
        "(products config, D=100)\n\n" + format_quant_markdown(quant_rows)
    )
    # online-serving QPS model. Preferred cost input: the EVAL-SHAPED
    # dispatch split (sample_batch + forward_logits, measured by bench.py's
    # serve section / serve_probe.py) — a serve dispatch IS that step.
    # Fallback when no split is available: the train step, pessimistic at
    # the reference batch (it additionally pays backward + update). Either
    # way the linear down-scaling to small buckets omits fixed per-dispatch
    # overhead and is optimistic there (serve_table docstring).
    if serve_sample_s or serve_forward_s:
        serve_rows = serve_table(
            serve_sample_s, 0.0, serve_forward_s, ref_batch=serve_ref_batch,
            buckets=(64, 256, 1024), hit_rates=(0.0, 0.5, 0.9),
            unique_frac=0.8, max_delay_ms=2.0,
            host_submit_us=host_submit_us,
            host_resolve_us=host_resolve_us,
        )
        serve_cost_note = (
            "Device cost per dispatch is the MEASURED eval-shaped split "
            f"(sample {serve_sample_s*1e3:.2f} ms +\nforward "
            f"{serve_forward_s*1e3:.2f} ms at batch {serve_ref_batch}; "
            f"source: {serve_source}) — the exact\nsample_batch + "
            "forward_logits stages a serve dispatch runs, no train-step "
            "proxy."
        )
    else:
        serve_rows = serve_table(
            step_s, 0.0, 0.0, ref_batch=1024, buckets=(64, 256, 1024),
            hit_rates=(0.0, 0.5, 0.9), unique_frac=0.8, max_delay_ms=2.0,
            host_submit_us=host_submit_us,
            host_resolve_us=host_resolve_us,
        )
        serve_cost_note = (
            "Device cost per dispatch is the measured TRAIN step at batch "
            "1024 (pessimistic: a serve\ndispatch runs the same sample + "
            "gather + forward but no backward/update — pass the\nmeasured "
            "split via --serve-sample-ms/--serve-forward-ms or a bench "
            "artifact with\nserve_sample_s to drop the proxy)."
        )
    serve_md = (
        "## Online serving: predicted QPS vs bucket / cache hit "
        "rate (quiver_tpu.serve)\n\n"
        + serve_cost_note
        + " Scaled linearly to each bucket (OPTIMISTIC at small\nbuckets: "
        "fixed per-dispatch overhead is omitted — see the serve_table "
        "docstring).\nThe measured counterpart with the real engine is "
        "scripts/serve_probe.py ->\nSERVE_r04.json (fused vs split, "
        "median-of-N), SERVE_r02.json (window sweep),\nSERVE_r01.json "
        "(cache/skew sweep).\n\n"
        + format_serve_markdown(serve_rows)
    )
    # one-vs-two-dispatch rows (round 11): the fixed per-execute overhead
    # paid once on the fused serve path, twice on the round-9 split path
    serve_dispatch_rows = []
    if serve_overhead_s:
        sc = (
            (serve_sample_s, serve_forward_s, serve_ref_batch)
            if (serve_sample_s or serve_forward_s)
            else (step_s, 0.0, 1024)
        )
        for dpf in (1, 2):
            serve_dispatch_rows += serve_table(
                sc[0], 0.0, sc[1], ref_batch=sc[2], buckets=(64, 256),
                hit_rates=(0.0, 0.5), unique_frac=0.8, max_delay_ms=2.0,
                dispatches_per_flush=dpf, dispatch_overhead_s=serve_overhead_s,
            )
        serve_md += (
            "\n\n### One-vs-two-dispatch (fused serve_step vs split "
            "sample+forward)\n\n"
            f"Fixed per-execute overhead {serve_overhead_s*1e3:.2f} ms "
            "(measured split-minus-fused delta\nor --serve-overhead-ms) "
            "paid once per flush fused, twice split; the win\nconcentrates "
            "at small (latency-bound) buckets.\n\n"
            + format_serve_markdown(serve_dispatch_rows)
        )
    # H-host distributed serving rows (quiver_tpu.serve.DistServeEngine):
    # same cost inputs, bucket split by seed ownership — per-shard width
    # bucket/H, the serve-shaped exchange priced at the DCN rate like the
    # training-side sampling exchange
    serve_cost = (
        (serve_sample_s, serve_forward_s, serve_ref_batch)
        if (serve_sample_s or serve_forward_s)
        else (step_s, 0.0, 1024)
    )
    dist_rows = []
    for hosts in (int(h) for h in args.serve_hosts.split(",")):
        dist_rows += serve_table(
            serve_cost[0], 0.0, serve_cost[1], ref_batch=serve_cost[2],
            buckets=(256,), hit_rates=(0.0, 0.5), unique_frac=0.8,
            max_delay_ms=2.0, hosts=hosts, out_dim=args.serve_out_dim,
            bandwidths={"dcn_bytes_per_s": args.dcn_gbps * 1e9},
            host_submit_us=host_submit_us,
            host_resolve_us=host_resolve_us,
        )
    serve_dist_md = (
        "## Distributed serving: predicted aggregate QPS vs host count "
        "(quiver_tpu.serve.dist)\n\n"
        "Seed-ownership routed engine at global bucket 256: each of H "
        "shards dispatches a\nbucket/H-wide sub-batch concurrently; one "
        "routed flush pays one shard dispatch plus\nthe serve-shaped "
        "exchange (H*H*L int32 ids out + H*H*L*C f32 logits back over "
        "DCN).\nAggregate QPS scales ~H-fold until the exchange term "
        "catches the shrinking dispatch.\nMeasured CPU-tier counterpart: "
        "scripts/serve_probe.py --hosts -> SERVE_r03.json\n(width shrink + "
        "wire bytes + in-run bit-parity; absolute QPS there shares one "
        "core).\n\n"
        + format_serve_markdown(dist_rows)
    )
    # round-23 host-mode fan-out rows: same cost inputs, routed dispatch
    # priced at ceil(H/F) * leg + merge instead of the collective
    # exchange — measured counterpart is bench_frontend.py --r03
    dist_fanout_rows = []
    if owner_fanout is not None:
        for hosts in (int(h) for h in args.serve_hosts.split(",")):
            dist_fanout_rows += serve_table(
                serve_cost[0], 0.0, serve_cost[1], ref_batch=serve_cost[2],
                buckets=(256,), hit_rates=(0.0, 0.5), unique_frac=0.8,
                max_delay_ms=2.0, hosts=hosts, out_dim=args.serve_out_dim,
                bandwidths={"dcn_bytes_per_s": args.dcn_gbps * 1e9},
                host_submit_us=host_submit_us,
                host_resolve_us=host_resolve_us,
                owner_fanout=owner_fanout, leg_merge_us=leg_merge_us,
            )
        serve_dist_md += (
            "\n\n### Host-mode concurrent owner fan-out (round 23)\n\n"
            f"Fan-out inputs: {fanout_source} — routed dispatch priced "
            f"at ceil(H/{owner_fanout}) legs\nplus a "
            f"{leg_merge_us:.0f} us join/apply merge, zero exchange "
            "bytes (direct owner legs on\nworker threads; "
            "`DistServeEngine` exchange='host'). Measured counterpart:\n"
            "scripts/bench_frontend.py --r03 -> FRONTEND_r03.json "
            "(sequential-vs-fan-out wall\nwith stall-shaped owners, "
            "bit-parity asserted in-run).\n\n"
            + format_serve_markdown(dist_fanout_rows)
        )
    # hot-shard replication table (round 13, ROADMAP item 3a): predicted
    # wire-side benefit of replicating the measured hot head on every
    # host, from the frequency sketch's head-concentration curve
    per_seed = (serve_cost[0] + serve_cost[1]) / max(serve_cost[2], 1)
    skew_bucket = 256
    skew_hosts = max(
        [int(h) for h in args.serve_hosts.split(",")] or [2]
    )
    if args.skew:
        with open(args.skew) as fh:
            skew_doc = json.load(fh)
        pts = [p for p in skew_doc.get("points", [])
               if p.get("skew_report")]
        pt = max(pts, key=lambda p: p.get("alpha", 0)) if pts else None
        cov_map = (pt or {}).get("skew_report", {}).get("top_coverage", {})
        cov = sorted((int(k), float(v)) for k, v in cov_map.items())
        skew_source = (
            f"{args.skew} measured top_coverage (alpha="
            f"{(pt or {}).get('alpha')})"
        )
    else:
        import math as _math

        harm = sum(r ** -args.skew_alpha
                   for r in range(1, args.skew_nodes + 1))
        cov, acc = [], 0.0
        ks = (64, 256, 1024, 4096)
        it = iter(ks)
        nxt = next(it)
        for r in range(1, max(ks) + 1):
            acc += r ** -args.skew_alpha / harm
            if r == nxt:
                cov.append((r, acc))
                nxt = next(it, None)
                if nxt is None:
                    break
        skew_source = (
            f"analytic Zipf(alpha={args.skew_alpha}) over "
            f"{args.skew_nodes} nodes"
        )
    skew_rows = skew_table(
        cov, hosts=skew_hosts, bucket=skew_bucket,
        out_dim=args.serve_out_dim,
        dispatch_s=per_seed * -(-skew_bucket // skew_hosts),
        bandwidths={"dcn_bytes_per_s": args.dcn_gbps * 1e9},
    )
    skew_md = (
        "## Hot-shard replication: predicted benefit from the measured "
        "access skew (round 13)\n\n"
        f"Coverage source: {skew_source}; hosts={skew_hosts}, global "
        f"bucket {skew_bucket}.\nMeasured counterpart: "
        "scripts/serve_probe.py --skew -> SERVE_r06.json "
        "(sketch-vs-exact overlap,\npredicted-vs-measured hit rate, "
        "owner imbalance).\n\n"
        + format_skew_markdown(skew_rows)
    )
    # -- round-14: disk/DRAM/HBM hit-mix pricing (tier_table) ------------
    # round-18: flush-ahead prefetch hit rate — a measured fraction (or
    # a TIER_r02 artifact carrying one) prices staged disk rows at the
    # DRAM-staging consume instead of the pooled backing read
    if args.tier_prefetch is None:
        pf_rate, pf_source = 0.0, "no prefetch (pass --tier-prefetch)"
    else:
        try:
            pf_rate = float(args.tier_prefetch)
            pf_source = f"--tier-prefetch {pf_rate}"
        except ValueError:
            with open(args.tier_prefetch) as fh:
                pf_rate = float(
                    json.load(fh)["prefetch_hit_rate_measured"]["median"]
                )
            pf_source = (f"{args.tier_prefetch} measured median "
                         "tier_prefetch hit rate")
    if args.tier:
        with open(args.tier) as fh:
            tier_doc = json.load(fh)
        cost = tier_doc["measured_row_costs_s"]
        t_cfg = tier_doc["config"]
        mixes = [("all_hbm", 1.0, 0.0, 0.0)]
        for label in ("static", "adaptive"):
            m = tier_doc[label]["runs"][-1]["gather_mix"]
            hbm, host = m.get("hbm", 0.0), m.get("host", 0.0)
            mixes.append((f"{label}_measured", hbm, host,
                          max(1.0 - hbm - host, 0.0)))
        workers = t_cfg.get("read_workers", 4)
        tier_rows = tier_table(
            mixes, bucket=t_cfg.get("max_batch", 32),
            dispatch_s=cost["dispatch_s"], hbm_row_s=cost["hbm"],
            host_row_s=cost["host"],
            disk_row_s=cost["disk_pooled"] * workers,
            feature_dim=t_cfg.get("dim", 100), read_workers=workers,
            prefetch_hit_rate=pf_rate,
        )
        tier_source = f"{args.tier} measured row costs + hit mixes"
    else:
        # labeled placeholders: page-cache-class host/disk split with a
        # 100 us cold-read per row — swap for bench.py tier_*_row_s /
        # TIER_r01.json measurements via --tier
        tier_rows = tier_table(
            [("all_hbm", 1.0, 0.0, 0.0),
             ("static_cold", 0.06, 0.14, 0.80),
             ("adapted", 0.26, 0.19, 0.55)],
            bucket=32, dispatch_s=3.5e-3, hbm_row_s=4e-6,
            host_row_s=6e-6, disk_row_s=1e-4, feature_dim=100,
            read_workers=4, prefetch_hit_rate=pf_rate,
        )
        tier_source = "analytic placeholder costs (pass --tier TIER_r01.json)"
    tier_md = (
        "## Tiered storage: disk/DRAM/HBM hit-mix pricing (round 14)\n\n"
        f"Cost source: {tier_source}.\n"
        f"Prefetch hit-rate source (round 18): {pf_source}.\n"
        "Measured counterpart: "
        "scripts/serve_probe.py --tiers -> TIER_r01.json (static vs\n"
        "sketch-driven adaptive placement, median-of-3, simulated cold-"
        "read latency\nlabeled in config) and --tiers --real-disk -> "
        "TIER_r02.json (page-cache-\ndefeated reads, mid-run hot-set "
        "shift, prefetch on/off/all-DRAM\ninterleaved median-of-3).\n\n"
        + format_tier_markdown(tier_rows)
    )
    # -- round-17: streaming-graph ingest pricing (delta_table) ----------
    # each cost labels its own provenance: one measured + one
    # placeholder input must never read as "measured" wholesale (the
    # tier/skew sections' labeling discipline), and an explicit 0 is a
    # measurement, not "unset"
    append_s = (10e-6 if args.stream_append_us is None
                else args.stream_append_us / 1e6)
    swap_s = (2e-3 if args.stream_swap_ms is None
              else args.stream_swap_ms / 1e3)
    if args.stream_append_us is not None and args.stream_swap_ms is not None:
        delta_source = "measured bench stream_append_s/stream_swap_s"
    elif args.stream_append_us is None and args.stream_swap_ms is None:
        # labeled placeholders — swap for bench.py's stream leg via
        # --bench BENCH_r*.json or the explicit flags
        delta_source = (
            "analytic placeholder costs (pass --bench or "
            "--stream-append-us/--stream-swap-ms)"
        )
    else:
        measured, missing = (
            ("stream_append_s", "stream_swap_s (placeholder 2 ms)")
            if args.stream_swap_ms is None
            else ("stream_swap_s", "stream_append_s (placeholder 10 us)")
        )
        delta_source = (
            f"measured bench {measured}; {missing} — pass both flags "
            "or --bench for a fully measured table"
        )
    delta_rows = delta_table(
        [("feed_trickle", 100), ("feed_busy", 2_000),
         ("fraud_burst", 20_000), ("ingest_storm", 200_000)],
        append_s_per_edge=append_s, swap_s_per_commit=swap_s,
        commit_period_s=args.stream_commit_s,
    )
    delta_md = (
        "## Streaming-graph ingest: delta-apply cost vs edge rate "
        "(round 17)\n\n"
        f"Cost source: {delta_source}; commit period "
        f"{args.stream_commit_s} s.\nMeasured counterpart: "
        "scripts/serve_probe.py --stream -> STREAM_r01.json (served "
        "Zipf\ntrace under live edge appends, empty-delta bit-parity, "
        "invalidation counts).\n\n"
        + format_delta_markdown(delta_rows)
    )
    # -- round-21: graph-lifecycle pricing (delta_table churn terms) -----
    lifecycle_md = None
    lifecycle_rows = []
    lifecycle_source = None
    if args.lifecycle:
        delete_s = (5e-6 if args.stream_delete_us is None
                    else args.stream_delete_us / 1e6)
        compact_s = (5e-3 if args.stream_compact_ms is None
                     else args.stream_compact_ms / 1e3)
        if (args.stream_delete_us is not None
                and args.stream_compact_ms is not None):
            lifecycle_source = (
                "measured bench stream_delete_s/stream_compact_s"
            )
        elif args.stream_delete_us is None and args.stream_compact_ms is None:
            lifecycle_source = (
                "analytic placeholder costs (pass --bench or "
                "--stream-delete-us/--stream-compact-ms)"
            )
        else:
            lifecycle_source = (
                "partially measured — pass both --stream-delete-us and "
                "--stream-compact-ms (or --bench) for a fully measured "
                "table"
            )
        lifecycle_rows = delta_table(
            [("feed_trickle", 100), ("feed_busy", 2_000),
             ("fraud_burst", 20_000), ("ingest_storm", 200_000)],
            append_s_per_edge=append_s, swap_s_per_commit=swap_s,
            commit_period_s=args.stream_commit_s,
            delete_frac=args.delete_frac,
            delete_s_per_edge=delete_s,
            compact_s_per_pass=compact_s,
            compact_every_commits=args.compact_every_commits,
        )
        lifecycle_md = (
            "## Graph lifecycle: steady-state churn + compaction "
            "(round 21)\n\n"
            f"Cost source: {lifecycle_source}; append/swap as the ingest "
            f"table above;\ndelete_frac {args.delete_frac} (deletes+TTL "
            "expiries per append — 1.0 is the\nflat-footprint regime), "
            f"compaction every {args.compact_every_commits:.0f} commits "
            "amortized into duty.\nMeasured counterpart: "
            "scripts/serve_probe.py --lifecycle -> LIFECYCLE_r01.json\n"
            "(appends+expiries at steady state under live Zipf traffic, "
            "flat reserve\noccupancy, in-run oracle parity).\n\n"
            + format_delta_markdown(lifecycle_rows)
        )
        # -- round-24: drain-vs-flip commit-stall re-pricing -------------
        if args.fence_mode == "zerostall":
            stall_us = (100.0 if args.stream_commit_stall_us is None
                        else args.stream_commit_stall_us)
            stall_source = (
                "measured serve_probe --stream-stall commit_stall_us"
                if args.stream_commit_stall_us is not None else
                "analytic placeholder flip hold (pass "
                "--stream-commit-stall-us from STREAM_r02.json)"
            )
            zerostall_rows = delta_table(
                [("feed_trickle", 100), ("feed_busy", 2_000),
                 ("fraud_burst", 20_000), ("ingest_storm", 200_000)],
                append_s_per_edge=append_s, swap_s_per_commit=swap_s,
                commit_period_s=args.stream_commit_s,
                delete_frac=args.delete_frac,
                delete_s_per_edge=delete_s,
                compact_s_per_pass=compact_s,
                compact_every_commits=args.compact_every_commits,
                commit_stall_us=stall_us,
                fence_mode="zerostall",
            )
            lifecycle_md += (
                "\n\n## Zero-stall commits: drain vs flip pricing "
                "(round 24)\n\n"
                f"Stall source: {stall_source}; churn terms as the "
                "lifecycle table above.\nThe fenced twin's per-commit "
                "stall is the whole drain+apply hold (the\nfence stall "
                "column above); zero-stall commits build off-fence and "
                "only\nhold the dispatch lock for the pointer flip, so "
                "duty is unchanged and\nthe stall column collapses to "
                "the flip hold.\nMeasured counterpart: "
                "scripts/serve_probe.py --stream-stall -> "
                "STREAM_r02.json\n(commit storm under saturated Zipf "
                "traffic, fenced-vs-zero-stall stall\nratio, on-commit "
                "p99, epoch-aware oracle parity).\n\n"
                + format_delta_markdown(zerostall_rows)
            )
    # -- round-19: link-prediction pricing (lp_table) --------------------
    lp_step_s = (2e-3 if args.lp_step_ms is None else args.lp_step_ms / 1e3)
    lp_head_s = (1e-6 if args.lp_head_us is None else args.lp_head_us / 1e6)
    if args.lp_step_ms is not None and args.lp_head_us is not None:
        lp_source = "measured bench temporal_step_s/lp_head_s"
    elif args.lp_step_ms is None and args.lp_head_us is None:
        lp_source = (
            "analytic placeholder costs (pass --bench or "
            "--lp-step-ms/--lp-head-us)"
        )
    else:
        lp_source = (
            "partially measured — pass both --lp-step-ms and "
            "--lp-head-us (or --bench) for a fully measured table"
        )
    lp_rows = lp_table(
        lp_step_s, args.lp_ref_batch, head_s_per_pair=lp_head_s,
    )
    lp_md = (
        "## Link-prediction serving: pair-QPS vs node-QPS (round 19)\n\n"
        f"Cost source: {lp_source} (ref batch {args.lp_ref_batch}).\n"
        "Measured counterpart: scripts/serve_probe.py --temporal -> "
        "WORKLOAD_r01.json\n(split-owner pairs through the exchange, "
        "temporal oracle parity in-run).\n\n"
        + format_lp_markdown(lp_rows)
    )
    print(md, file=sys.stderr)
    print("\n" + fetch_md, file=sys.stderr)
    print("\n" + quant_md, file=sys.stderr)
    print("\n" + serve_md, file=sys.stderr)
    print("\n" + serve_dist_md, file=sys.stderr)
    print("\n" + skew_md, file=sys.stderr)
    print("\n" + tier_md, file=sys.stderr)
    print("\n" + delta_md, file=sys.stderr)
    if lifecycle_md is not None:
        print("\n" + lifecycle_md, file=sys.stderr)
    print("\n" + lp_md, file=sys.stderr)
    if args.out:
        header = (
            "# Predicted multi-chip scaling (static model)\n\n"
            "Reference publishes measured 1-4 GPU scaling "
            "(docs/Introduction_en.md:144-158: epochs 11.1 / 6.0 / 4.0 / 3.2 s);\n"
            "this table is the analytic counterpart for the TPU layouts — see\n"
            "`quiver_tpu/parallel/scaling.py` for the model, assumptions, and\n"
            "how to swap predictions for measurements on real hardware.\n"
            f"Single-chip step source: {source}.\n\n"
        )
        with open(args.out, "w") as fh:
            fh.write(
                header + md + "\n\n" + fetch_md + "\n\n" + quant_md
                + "\n\n" + serve_md + "\n\n" + serve_dist_md
                + "\n\n" + skew_md + "\n\n" + tier_md + "\n\n"
                + delta_md + "\n\n"
                + ((lifecycle_md + "\n\n") if lifecycle_md else "")
                + lp_md + "\n"
            )
    print(json.dumps({
        "step_s_1chip": step_s,
        "source": source,
        "serve_cost_source": (
            serve_source if (serve_sample_s or serve_forward_s)
            else "train-step proxy"
        ),
        "serve_sample_s": serve_sample_s,
        "serve_forward_s": serve_forward_s,
        "serve_overhead_s": serve_overhead_s,
        "host_submit_us": host_submit_us,
        "host_resolve_us": host_resolve_us,
        "host_submit_source": host_submit_source,
        "rows": [r._asdict() for r in rows],
        "sharded_fetch": [r._asdict() for r in fetch_rows],
        "quant_fetch": [r._asdict() for r in quant_rows],
        "serve": [r._asdict() for r in serve_rows],
        "serve_one_vs_two_dispatch": [r._asdict() for r in serve_dispatch_rows],
        "serve_dist": [r._asdict() for r in dist_rows],
        "owner_fanout": owner_fanout,
        "leg_merge_us": leg_merge_us,
        "fanout_source": fanout_source,
        "serve_dist_fanout": [r._asdict() for r in dist_fanout_rows],
        "skew_source": skew_source,
        "skew_replication": [r._asdict() for r in skew_rows],
        "delta_source": delta_source,
        "delta_table": [r._asdict() for r in delta_rows],
        "lifecycle_source": lifecycle_source,
        "lifecycle_table": [r._asdict() for r in lifecycle_rows],
        "lp_source": lp_source,
        "lp_table": [r._asdict() for r in lp_rows],
    }))


if __name__ == "__main__":
    main()

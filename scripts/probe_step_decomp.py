"""Decompose the e2e train step's 44 ms: sampling / reindex / gather /
model+grad, each as its own scanned program with floor-corrected windows.

With the true gather rate (~94M rows/s, PERF_NOTES round-4 correction) the
gather should be ~9 ms of the 44 ms dedup step — this probe finds where the
rest goes. Same measurement discipline as bench.py.
"""
import os
import sys
import time

import numpy as np

# self-path instead of PYTHONPATH: overriding PYTHONPATH clobbers the
# axon sitecustomize dir (/root/.axon_site) and silently unregisters the
# TPU backend — append, never replace
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # bench.py: graph cache + compile cache helpers

bench.enable_compile_cache()

import jax
import jax.numpy as jnp
from jax import lax

from quiver_tpu.pyg.sage_sampler import (
    sample_and_gather_dedup,
    sample_and_gather_fused,
    sample_dense_fused,
    sample_dense_pure,
)

ITERS = 100
SIZES = (15, 10, 5)
CAPS = (16384, 135168, 499712)  # the bench's calibrated caps


def timed(fn, *args):
    float(fn(*args))  # block_until_ready can return EARLY via the tunnel
    best = None
    for _ in range(2):
        t0 = time.time()
        float(fn(*args))
        dt = time.time() - t0
        best = dt if best is None else min(best, dt)
    return best


def main():
    indptr_np, indices_np = bench.build_graph()
    indptr = jax.device_put(jnp.asarray(indptr_np.astype(np.int32)))
    indices = jax.device_put(jnp.asarray(indices_np.astype(np.int32)))
    int(indptr[-1]), int(indices[-1])
    n = indptr.shape[0] - 1
    table = jax.jit(lambda k: jax.random.normal(k, (n, 100), jnp.float32))(
        jax.random.key(7)
    )
    rng = np.random.default_rng(1)
    seeds = jax.device_put(
        jnp.asarray(rng.integers(0, n, (24, 1024)).astype(np.int32))
    )
    floor = bench.measure_rpc_floor()

    def scan_over(body):
        @jax.jit
        def run(ip, ix, tab, key0, seeds_all):
            m = seeds_all.shape[0]

            def step(acc, i):
                key = jax.random.fold_in(key0, i)
                return acc + body(ip, ix, tab, key, seeds_all[i % m]), None

            acc, _ = lax.scan(step, jnp.float32(0), jnp.arange(ITERS, dtype=jnp.int32))
            return acc

        return run

    def report(name, run):
        dt = timed(run, indptr, indices, table, jax.random.key(0), seeds)
        ms = (dt - floor) / ITERS * 1e3
        print(f"  {name:26s}: {ms:6.2f} ms/iter")
        return ms

    # a. fused sampling only
    def fused_sample(ip, ix, tab, key, s):
        ds = sample_dense_fused(ip, ix, key, s, SIZES)
        return ds.n_id.sum(dtype=jnp.float32)

    # b. dedup sampling only (sorts + reindex included)
    def dedup_sample(ip, ix, tab, key, s):
        ds = sample_dense_pure(ip, ix, key, s, SIZES, CAPS)
        return ds.n_id.sum(dtype=jnp.float32)

    # c. dedup sample + leaf gather (no model)
    def dedup_gather(ip, ix, tab, key, s):
        ds, x = sample_and_gather_dedup(ip, ix, tab, key, s, SIZES, CAPS)
        return x.sum(dtype=jnp.float32)

    # d. fused sample + interleaved gather (no model)
    def fused_gather(ip, ix, tab, key, s):
        ds, x = sample_and_gather_fused(ip, ix, tab, key, s, SIZES)
        return x.sum(dtype=jnp.float32)

    # e. gather only, dedup-width take from the table
    W = 811_008
    ids = jax.device_put(
        jnp.asarray(rng.integers(0, n, W).astype(np.int32))
    )

    @jax.jit
    def pure_gather(tab, ids):
        def stepf(acc, i):
            sh = (ids + i * 977) % n
            return acc + jnp.take(tab, sh, axis=0).sum(dtype=jnp.float32), None

        acc, _ = lax.scan(stepf, jnp.float32(0), jnp.arange(ITERS, dtype=jnp.int32))
        return acc

    for name, body in (
        ("a fused sample only", fused_sample),
        ("b dedup sample only", dedup_sample),
        ("c dedup sample+gather", dedup_gather),
        ("d fused sample+gather", fused_gather),
    ):
        report(name, scan_over(body))
    dt = timed(pure_gather, table, ids)
    print(f"  e pure take {W} rows       : {(dt-floor)/ITERS*1e3:6.2f} ms/iter")


if __name__ == "__main__":
    main()

"""Probe: XLA row-gather rate vs row width, and packed-row gather schemes.

PERF_NOTES.md records the hot-gather descriptor wall: ~20M rows/s for
dim<=128, but ~26M rows/s at dim 256. If rate keeps rising with row width,
storing the feature table packed ([N/p, p*D]) and selecting the needed
D-slice on-chip beats the plain gather even with p-1 wasted lanes.

Two sections:
  1. rate-vs-dim curve: f32 dims 100..1024 (+bf16), constant ~1 GB table.
  2. end-to-end packed-select: deliver [W, 100] useful f32 rows from a
     pack-p table via take(ids >> log2 p) + per-row half select.

Measurement discipline (PERF_NOTES.md): tables generated ON DEVICE, passed
as jit ARGUMENTS, iterations scanned in-jit, timing ended with a dependent
float() fetch. Run with `python -u`, nothing else on the machine.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

W = 262_144
ITERS = 10
TARGET_BYTES = 980_000_000  # ~ the products table, constant across dims


def make_gather(iters):
    @jax.jit
    def gather_many(tab, idx):
        def body(acc, i):
            shifted = (idx + i * 977) % tab.shape[0]
            return acc + jnp.take(tab, shifted, axis=0).sum(dtype=jnp.float32), None

        acc, _ = lax.scan(body, jnp.float32(0), jnp.arange(iters, dtype=jnp.int32))
        return acc

    return gather_many


def timed(fn, *args):
    float(fn(*args))  # compile + warm
    t0 = time.time()
    float(fn(*args))
    return time.time() - t0


def section_rate_vs_dim():
    print("== rate vs dim (gather W=%d rows, %d iters in-jit) ==" % (W, ITERS))
    gather_many = make_gather(ITERS)
    for dtype, dsize in ((jnp.float32, 4), (jnp.bfloat16, 2)):
        for dim in (100, 128, 200, 256, 400, 512, 800, 1024):
            n = TARGET_BYTES // (dim * dsize)
            key = jax.random.key(dim)
            tab = jax.random.normal(key, (n, dim), dtype=dtype)
            idx = jax.random.randint(jax.random.key(7), (W,), 0, n, dtype=jnp.int32)
            tab, idx = jax.block_until_ready((tab, idx))
            dt = timed(gather_many, tab, idx)
            rows_s = ITERS * W / dt
            gbps = rows_s * dim * dsize / 1e9
            print(
                f"  {jnp.dtype(dtype).name:8s} dim={dim:5d} N={n:8d}: "
                f"{rows_s/1e6:6.1f}M rows/s  {gbps:7.2f} GB/s raw"
            )
            del tab


def section_packed_select():
    """Deliver [W, 100] useful f32 rows from a pack-p table.

    Base table conceptually [N0, 100] f32, N0 = 2.45M (products). Packed
    table [N0/p, p*100]; requested ids uniform in [0, N0). Scheme: take the
    packed row id>>log2(p), then select the 100-wide slice (id % p) with a
    one-hot contraction-free where-chain (p is tiny and static).
    """
    print("== packed-select end-to-end (useful D=100 f32, W=%d) ==" % W)
    n0, d = 2_449_029, 100

    for p in (1, 2, 4, 8):
        npk = (n0 + p - 1) // p
        key = jax.random.key(p)
        tab = jax.random.normal(key, (npk, p * d), dtype=jnp.float32)
        idx = jax.random.randint(jax.random.key(9), (W,), 0, n0, dtype=jnp.int32)
        tab, idx = jax.block_until_ready((tab, idx))

        @jax.jit
        def run(tab, idx, p=p):
            def body(acc, i):
                ids = (idx + i * 977) % n0
                packed = jnp.take(tab, ids // p, axis=0)  # [W, p*d]
                if p == 1:
                    rows = packed
                else:
                    parts = packed.reshape(W, p, d)
                    sel = jax.nn.one_hot(ids % p, p, dtype=packed.dtype)
                    rows = jnp.einsum("wp,wpd->wd", sel, parts)
                return acc + rows.sum(dtype=jnp.float32), None

            acc, _ = lax.scan(body, jnp.float32(0), jnp.arange(ITERS, dtype=jnp.int32))
            return acc

        dt = timed(run, tab, idx)
        rows_s = ITERS * W / dt
        useful_gbps = rows_s * d * 4 / 1e9
        print(
            f"  pack={p}: {rows_s/1e6:6.1f}M useful rows/s  "
            f"{useful_gbps:6.2f} GB/s useful ({useful_gbps*p:7.2f} GB/s raw)"
        )
        del tab


def section_packed_select_dynslice():
    """pack-p with per-row dynamic-slice select instead of one-hot einsum."""
    print("== packed-select via vmap dynamic_slice ==")
    n0, d = 2_449_029, 100
    for p in (2, 4):
        npk = (n0 + p - 1) // p
        tab = jax.random.normal(jax.random.key(p + 100), (npk, p * d), jnp.float32)
        idx = jax.random.randint(jax.random.key(9), (W,), 0, n0, dtype=jnp.int32)
        tab, idx = jax.block_until_ready((tab, idx))

        @jax.jit
        def run(tab, idx, p=p):
            def body(acc, i):
                ids = (idx + i * 977) % n0
                packed = jnp.take(tab, ids // p, axis=0)  # [W, p*d]
                off = (ids % p) * d
                rows = jax.vmap(
                    lambda row, o: lax.dynamic_slice(row, (o,), (d,))
                )(packed, off)
                return acc + rows.sum(dtype=jnp.float32), None

            acc, _ = lax.scan(body, jnp.float32(0), jnp.arange(ITERS, dtype=jnp.int32))
            return acc

        dt = timed(run, tab, idx)
        rows_s = ITERS * W / dt
        print(
            f"  pack={p}: {rows_s/1e6:6.1f}M useful rows/s  "
            f"{rows_s*d*4/1e9:6.2f} GB/s useful"
        )
        del tab


if __name__ == "__main__":
    print("devices:", jax.devices())
    section_rate_vs_dim()
    section_packed_select()
    section_packed_select_dynslice()

"""Host-path frontend benchmark, round 22: the whole submit→drained→
delivered path on a MOCKED device, phase by phase.

Round 20 priced admission (scalar vs batch submit); round 22 vectorized
the drain/delivery half (`_resolve` block resolution, `put_many`,
`ResultBatch`/`results_many`), so this benchmark now times FOUR phases
per leg: SUBMIT (admission), FLUSH-ASSEMBLE (drain + seal busy time,
from the engine's span recorder), RESOLVE (stage-3 busy time), and
DELIVER (`results_many` over the returned handles). The device leg is
stubbed out — the engine's `_dispatch` returns canned read-only logits —
so wall time IS host time (the TIER_r02 discipline: the thing being
priced is isolated in-run, and scalar/batch repeats are interleaved so
machine drift hits both alike).

``total`` keeps its r01 meaning — submit→drained wall — so the
trajectory stays comparable: FRONTEND_r01.json's node x1 batch leg is
read at run time and the r02 total-path throughput must be >= 3x it
(asserted in-run, non-smoke). DELIVER is timed separately, after the
drain, exactly as r01 left it untimed.

Resolve-path BIT-PARITY is asserted in-run on node and temporal
traffic: each parity pair drives the same trace through a block-resolve
engine and a ``_scalar_resolve=True`` twin (the pre-round-22 per-slot
loop, kept as the reference) and requires byte-identical logits,
byte-identical dispatch logs, identical cache contents/LRU order, and —
on the journal-on pair — identical journal event sequences.

Legs: {node, temporal, pair} traffic x {scalar submit loop, one
`submit_many`} x {1, 4} submit threads (the r01 leg names).

Artifact: FRONTEND_r02.json with per-leg, per-phase seconds and us/req,
the canonical ``host_submit_us`` AND the new ``host_resolve_us`` /
``host_deliver_us`` (batch path, node traffic, 1 thread) that price
`scaling.serve_table(host_submit_us=, host_resolve_us=)` via
``scripts/scaling_model.py --frontend``. Asserted in-run: every leg's
batch path beats its scalar path on BOTH the submit phase and the
total (submit→drained) wall; best batch-vs-scalar submit ratio >= 10x
(non-smoke); node x1 batch total >= 3x FRONTEND_r01's (non-smoke);
resolve bit-parity on node + temporal traffic (always, --smoke
included).
"""

import argparse
import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N_NODES = 1000
DIM = 16
SIZES = [4, 4]
OUT_DIM = 5
# flush-deferral bucket: larger than any default trace, so no inline
# fill-flush lands inside the measured submit window (with --requests
# above this, fills flush inline and the submit phase honestly includes
# them — the ratio assert still holds, with less margin)
MAX_BATCH = 4096
SEED = 7


def git_revision() -> str:
    try:
        return subprocess.check_output(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            text=True,
        ).strip()
    except Exception:
        return "unknown"


def make_graph():
    rng = np.random.default_rng(0)
    src = rng.integers(0, N_NODES, 6000)
    dst = rng.integers(0, N_NODES, 6000)
    keep = src != dst
    return np.stack([src[keep], dst[keep]])


def mocked(engine_cls):
    """Subclass an engine with the device leg stubbed: `_dispatch`
    returns canned read-only logits sized to the flush bucket. Seal
    still pads, draws the sampler key, and writes the dispatch log —
    the full host path runs; only the execute call is gone. The canned
    rows are DISTINCT (row i != row j), so the resolve-parity asserts
    catch a row mis-mapping, not just a wholesale swap."""

    canned = np.arange(
        MAX_BATCH * OUT_DIM, dtype=np.float32
    ).reshape(MAX_BATCH, OUT_DIM)
    canned.setflags(write=False)

    class Mocked(engine_cls):
        def _dispatch(self, fl):
            with self._lock:
                self.stats.dispatch_calls += 1
                self.stats.execute_calls += 1
            return canned

    Mocked.__name__ = f"Mocked{engine_cls.__name__}"
    return Mocked


def drain(eng):
    while eng._drainable():
        eng.flush()


def stage_busy(eng) -> dict:
    """Per-stage busy seconds summed from the engine's span recorder at
    full precision (`overlap_summary` rounds to 0.1 ms — too coarse for
    sub-ms phases)."""
    busy = {}
    for stage, t0, t1 in eng.stats.spans:
        busy[stage] = busy.get(stage, 0.0) + (t1 - t0)
    return busy


def drive(eng, ids, ts, n_threads, batched):
    """Submit the whole trace (scalar loop or one submit_many per
    thread-chunk), drain, then deliver every handle. Returns a dict of
    phase walls: ``submit`` (admission), ``total`` (submit→drained, the
    r01 meaning — DELIVER is outside it), ``deliver`` (results_many
    over the handles), plus the span-recorded ``assemble``/``resolve``
    busy seconds of the drain."""
    chunk_ix = np.array_split(np.arange(ids.shape[0]), n_threads)
    handles = [None] * n_threads
    errs = []

    def run(slot, ix):
        try:
            if batched:
                if ts is None:
                    handles[slot] = eng.submit_many(ids[ix])
                else:
                    handles[slot] = eng.submit_many(ids[ix], t=ts[ix])
            elif ts is None:
                handles[slot] = [eng.submit(int(ids[i])) for i in ix]
            else:
                handles[slot] = [
                    eng.submit(int(ids[i]), t=float(ts[i])) for i in ix
                ]
        except Exception as exc:  # a failed leg must not record a time
            errs.append(exc)

    threads = [
        threading.Thread(target=run, args=(slot, ix))
        for slot, ix in enumerate(chunk_ix)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    submit_wall = time.perf_counter() - t0
    drain(eng)
    total_wall = time.perf_counter() - t0
    if errs:
        raise errs[0]
    t_d0 = time.perf_counter()
    rows = [eng.results_many(h) for h in handles]
    deliver_wall = time.perf_counter() - t_d0
    busy = stage_busy(eng)
    n_rows = sum(r.shape[0] for r in rows)
    assert n_rows == ids.shape[0], (n_rows, ids.shape[0])
    return {
        "submit": submit_wall,
        "total": total_wall,
        "deliver": deliver_wall,
        "assemble": busy.get("assemble", 0.0),
        "resolve": busy.get("resolve", 0.0),
    }


def assert_resolve_parity(make_pair, ids, ts, label, journal_on):
    """Drive the same trace through a block-resolve engine and its
    ``_scalar_resolve=True`` twin; require byte-identical delivered
    logits, byte-identical dispatch logs, identical cache contents in
    LRU order, and (journal-on) identical event sequences."""
    a = make_pair()
    b = make_pair()
    b._scalar_resolve = True
    ha = a.submit_many(ids) if ts is None else a.submit_many(ids, t=ts)
    hb = b.submit_many(ids) if ts is None else b.submit_many(ids, t=ts)
    drain(a)
    drain(b)
    ra = a.results_many(ha)
    rb = b.results_many(hb)
    assert ra.tobytes() == rb.tobytes(), (
        f"{label}: block-resolve logits differ from scalar resolve"
    )
    la, lb = a.dispatch_log, b.dispatch_log
    assert len(la) == len(lb) and len(la) > 0, (label, len(la), len(lb))
    for ea, eb in zip(la, lb):
        assert len(ea) == len(eb), (label, ea, eb)
        for xa, xb in zip(ea, eb):
            if isinstance(xa, np.ndarray):
                assert xa.tobytes() == xb.tobytes(), (
                    f"{label}: dispatch log arrays differ"
                )
            else:
                assert xa == xb, (f"{label}: dispatch log fields differ",
                                  xa, xb)
    assert a.cache.keys() == b.cache.keys(), (
        f"{label}: cache contents / LRU order differ "
        f"(put_many vs scalar put)"
    )
    if journal_on:
        sa = [e[1:] for e in a.journal.snapshot() if e[1] != "window_wait"]
        sb = [e[1:] for e in b.journal.snapshot() if e[1] != "window_wait"]
        assert sa == sb, f"{label}: journal event sequences differ"
    return ra


# -- round-23 r03 leg: routed-dispatch wall, sequential vs fan-out -----------

R03_MAX_BATCH = 64


class StallOwner:
    """Stall-shaped mocked owner for the r03 leg: ``predict`` sleeps
    (`time.sleep` releases the GIL, the same shape as XLA's blocking
    execute) then returns rows derived from the ids — distinct per id
    and deterministic, so the parity asserts catch a row mis-mapping
    between schedulers, not just a wholesale swap."""

    def __init__(self, stall_s: float, out_dim: int):
        self.stall_s = stall_s
        self.out_dim = out_dim
        self.calls = 0

    def predict(self, ids, t=None, tenants=None):
        self.calls += 1
        if self.stall_s > 0:
            time.sleep(self.stall_s)
        ids = np.asarray(ids, np.int64).astype(np.float32)
        cols = np.arange(self.out_dim, dtype=np.float32)
        return ids[:, None] * 10.0 + cols[None, :]


def _deep_eq(xa, xb) -> bool:
    if isinstance(xa, np.ndarray) or isinstance(xb, np.ndarray):
        return (isinstance(xa, np.ndarray) and isinstance(xb, np.ndarray)
                and xa.dtype == xb.dtype and xa.shape == xb.shape
                and xa.tobytes() == xb.tobytes())
    if isinstance(xa, (list, tuple)):
        return (isinstance(xb, (list, tuple)) and len(xa) == len(xb)
                and all(_deep_eq(a, b) for a, b in zip(xa, xb)))
    return xa == xb


def _routed(hosts, temporal, stall_s, sequential, faults=None, **cfg_kw):
    from quiver_tpu.serve import DistServeConfig, DistServeEngine
    from quiver_tpu.workloads import TemporalDistServeEngine

    g2h = (np.arange(N_NODES) % hosts).astype(np.int32)
    owners = {h: StallOwner(stall_s, OUT_DIM) for h in range(hosts)}
    base = dict(
        hosts=hosts, max_batch=R03_MAX_BATCH, max_delay_ms=1e9,
        max_in_flight=1, exchange="host", record_dispatches=True,
        cache_entries=0, journal_events=1 << 15,
        sequential_legs=sequential, fault_injector=faults,
    )
    base.update(cfg_kw)
    cfg = DistServeConfig(**base)
    if temporal:
        return TemporalDistServeEngine(owners, g2h, OUT_DIM, config=cfg,
                                       t_quantum=4.0)
    return DistServeEngine(owners, g2h, OUT_DIM, config=cfg)


def _drive_routed(eng, ids, ts):
    """Time submit→drained: the trace is larger than ``max_batch`` so
    fill-flushes dispatch inside ``submit_many`` too — with stall-shaped
    owners the submit+drain wall IS the routed-dispatch wall (the host
    bookkeeping share is priced by the r01/r02 legs and is microseconds
    against the injected stalls)."""
    t0 = time.perf_counter()
    handles = (eng.submit_many(ids) if ts is None
               else eng.submit_many(ids, t=ts))
    while eng._drainable():
        eng.flush()
    wall = time.perf_counter() - t0
    rows = eng.results_many(handles)
    return wall, rows


def _collect_routed(eng, ids, ts):
    """Scalar-collect drive for the parity/fault legs: per-request
    (row bytes | error string) outcomes — slot errors stay per-request
    (the round-15 isolation contract), so a faulted run still yields a
    complete, comparable outcome vector."""
    handles = [
        eng.submit(int(n)) if ts is None
        else eng.submit(int(n), t=float(t))
        for n, t in zip(ids, ts if ts is not None else ids)
    ]
    while eng._drainable():
        eng.flush()
    out = []
    for h in handles:
        try:
            out.append(h.result(timeout=60).tobytes())
        except Exception as exc:
            out.append(f"{type(exc).__name__}: {exc}")
    return out


def _journal_stream(eng):
    return [e[1:] for e in eng.journal.snapshot() if e[1] != "window_wait"]


def _r03_parity(hosts, temporal, stall_s, ids, ts, label, fault_seed=None):
    """Drive the same trace through a ``sequential_legs=True`` router and
    the concurrent fan-out; require bit-identical per-request outcomes
    (logits bytes / error strings), dispatch logs, journal streams,
    owner-health state, hedge events — and, with a seeded `FaultSpec`
    plan active, identical fault firings (`events()`, the sorted view —
    the raw log's APPEND order is the one thing concurrency may
    reorder)."""
    from quiver_tpu.serve import FaultInjector

    views = []
    for sequential in (True, False):
        inj = (FaultInjector.seeded(
                   owners=range(hosts), n_faults=6, seed=fault_seed,
                   fid_range=(1, 6), kinds=("error", "stall", "kill"),
                   stall_s=stall_s,
               ) if fault_seed is not None else None)
        eng = _routed(hosts, temporal, stall_s, sequential, faults=inj)
        out = _collect_routed(eng, ids, ts)
        views.append({
            "out": out,
            "dispatch_log": eng.dispatch_log,
            "journal": _journal_stream(eng),
            "owner_health": eng.owner_health(),
            "hedge_events": eng.hedge_events(),
            "faults": inj.events() if inj is not None else None,
        })
    seq, fan = views
    assert seq["out"] == fan["out"], (
        f"{label}: per-request outcomes differ between sequential and "
        f"fan-out legs"
    )
    assert _deep_eq(seq["dispatch_log"], fan["dispatch_log"]), (
        f"{label}: dispatch logs differ"
    )
    assert seq["journal"] == fan["journal"], (
        f"{label}: journal event streams differ"
    )
    assert seq["owner_health"] == fan["owner_health"], (
        f"{label}: owner-health state differs"
    )
    assert seq["hedge_events"] == fan["hedge_events"], (
        f"{label}: hedge events differ"
    )
    assert seq["faults"] == fan["faults"], (
        f"{label}: fault firings differ"
    )
    return seq


def run_r03(args) -> None:
    """The round-23 routed-dispatch benchmark: H stall-shaped mocked
    owners under the REAL router, sequential vs fan-out back to back
    (interleaved repeats, best-of), bit-parity asserted in-run at hosts
    2/4 on node and temporal traffic plus a seeded fault plan on node
    traffic, and the r03 scaling keys (``owner_fanout`` /
    ``leg_merge_us``) written to FRONTEND_r03.json."""
    n = min(args.requests, N_NODES)
    stall_s = 0.002 if args.smoke else 0.02
    parity_stall_s = 0.001 if args.smoke else 0.005
    if args.smoke:
        n = min(n, 128)
    rng = np.random.default_rng(SEED)
    ids = rng.permutation(N_NODES)[:n].astype(np.int64)
    ts = rng.uniform(60.0, 90.0, n).astype(np.float32)
    n_flushes = -(-n // R03_MAX_BATCH)

    # -- bit-parity: sequential twin vs fan-out, all surfaces ------------
    parity_legs = []
    for hosts in (2, 4):
        for temporal in (False, True):
            label = (f"r03/{'temporal' if temporal else 'node'}"
                     f"/hosts{hosts}")
            _r03_parity(hosts, temporal, parity_stall_s, ids,
                        ts if temporal else None, label)
            parity_legs.append(label)
        flabel = f"r03/node/hosts{hosts}/faults"
        _r03_parity(hosts, False, parity_stall_s, ids, None, flabel,
                    fault_seed=23)
        parity_legs.append(flabel)
    print(f"r03 bit-parity: {len(parity_legs)} legs OK (outcomes + "
          f"dispatch logs + journal + owner-health + hedge + fault "
          f"events)", file=sys.stderr)

    # -- routed-dispatch wall: sequential vs fan-out ---------------------
    legs = []
    speedups = {}
    for hosts in (2, 4):
        for temporal in (False, True):
            name = "temporal" if temporal else "node"
            best = {True: float("inf"), False: float("inf")}
            for _ in range(args.repeats):
                # interleave so machine drift hits both schedulers alike
                for sequential in (True, False):
                    eng = _routed(hosts, temporal, stall_s, sequential,
                                  journal_events=0)
                    wall, rows = _drive_routed(
                        eng, ids, ts if temporal else None
                    )
                    assert rows.shape == (n, OUT_DIM)
                    best[sequential] = min(best[sequential], wall)
            speedup = round(best[True] / best[False], 2)
            leg = {
                "traffic": name,
                "hosts": hosts,
                "requests": n,
                "flushes": n_flushes,
                "owner_stall_ms": stall_s * 1e3,
                "routed_wall_s_sequential": round(best[True], 6),
                "routed_wall_s_fanout": round(best[False], 6),
                "fanout_speedup": speedup,
                "wall_per_flush_ms_sequential": round(
                    best[True] / n_flushes * 1e3, 3
                ),
                "wall_per_flush_ms_fanout": round(
                    best[False] / n_flushes * 1e3, 3
                ),
            }
            legs.append(leg)
            speedups[(name, hosts)] = speedup
            print(
                f"r03 {name} hosts={hosts}: sequential "
                f"{best[True]*1e3:.1f} ms, fan-out {best[False]*1e3:.1f} "
                f"ms over {n_flushes} flushes ({speedup:.2f}x)",
                file=sys.stderr,
            )

    if not args.smoke:
        for (name, hosts), s in speedups.items():
            bar = 3.0 if hosts >= 4 else 1.7
            assert s >= bar, (
                f"r03 {name} hosts={hosts} fan-out speedup {s:.2f}x < "
                f"{bar}x with stall-shaped owners"
            )

    # the r03 scaling keys: the headline hosts=4 node leg. merge =
    # fan-out wall per flush minus one stall (the leg floor) — the
    # join/apply host cost serve_table(leg_merge_us=) prices
    head = next(l for l in legs if l["traffic"] == "node"
                and l["hosts"] == 4)
    leg_merge_us = max(
        0.0,
        round((head["routed_wall_s_fanout"] / n_flushes - stall_s) * 1e6,
              3),
    )
    out = {
        "metric": "bench_frontend_r03",
        "git_revision": git_revision(),
        "config": {
            "n_nodes": N_NODES,
            "requests": n,
            "repeats": args.repeats,
            "max_batch": R03_MAX_BATCH,
            "owner_stall_ms": stall_s * 1e3,
            "mocked_owners": True,
            "smoke": bool(args.smoke),
            "methodology": (
                "real DistServeEngine/TemporalDistServeEngine routers "
                "(exchange='host') over H stall-shaped mocked owners "
                "(sleep-in-predict, GIL-releasing); drain wall timed as "
                "the routed-dispatch wall; sequential_legs=True vs the "
                "concurrent fan-out interleaved, best-of-repeats; "
                "bit-parity (per-request outcomes + dispatch logs + "
                "journal + owner-health + hedge + fault events) "
                "asserted in-run at hosts 2/4 on node and temporal "
                "traffic and under a seeded FaultSpec plan (node)"
            ),
        },
        "legs": legs,
        "parity_legs": parity_legs,
        # the serve_table(owner_fanout=, leg_merge_us=) inputs
        "owner_fanout": 4,
        "leg_merge_us": leg_merge_us,
        "routed_speedup_hosts4": speedups[("node", 4)],
        "routed_speedup_hosts2": speedups[("node", 2)],
        "asserts": {
            "bit_parity_all_legs": True,
            "speedup_ge_3x_hosts4": None if args.smoke else True,
            "speedup_ge_1p7x_hosts2": None if args.smoke else True,
        },
    }
    path = args.out
    if path is None and not args.smoke:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "FRONTEND_r03.json",
        )
    if path:
        with open(path, "w") as fh:
            json.dump(out, fh, indent=1)
            fh.write("\n")
        print(f"wrote {path}", file=sys.stderr)
    print(json.dumps({k: out[k] for k in
                      ("owner_fanout", "leg_merge_us",
                       "routed_speedup_hosts4",
                       "routed_speedup_hosts2")}))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=4000,
                    help="requests per measurement")
    ap.add_argument("--repeats", type=int, default=3,
                    help="interleaved scalar/batch repeats; best-of wins")
    ap.add_argument("--threads", default="1,4")
    ap.add_argument("--out", default=None,
                    help="artifact path (default FRONTEND_r02.json at the "
                         "repo root; --smoke writes nothing unless given)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny trace for CI: asserts batch >= scalar "
                         "(submit AND total) + resolve parity only")
    ap.add_argument("--r03", action="store_true",
                    help="run the round-23 routed-dispatch leg instead: "
                         "sequential vs fan-out over stall-shaped mocked "
                         "owners -> FRONTEND_r03.json")
    args = ap.parse_args()
    if args.r03:
        if args.requests == 4000:  # the r02 default is too long here
            args.requests = 512
        run_r03(args)
        return
    if args.smoke:
        args.requests = min(args.requests, 600)
        args.repeats = min(args.repeats, 2)

    from quiver_tpu import CSRTopo
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.pyg.sage_sampler import GraphSageSampler
    from quiver_tpu.serve import ServeConfig, ServeEngine
    from quiver_tpu.serve.trace_gen import lp_trace, temporal_trace, zipfian_trace
    from quiver_tpu.workloads import TemporalServeEngine, TemporalTiledGraph

    topo = CSRTopo(edge_index=make_graph())
    base_ts = np.random.default_rng(11).uniform(
        0.0, 50.0, topo.indices.shape[0]
    ).astype(np.float32)
    feat = np.zeros((N_NODES, DIM), np.float32)
    model = GraphSAGE(hidden_dim=16, out_dim=OUT_DIM, num_layers=2,
                      dropout=0.0)
    # the mocked `_dispatch` never touches params and `warmup` is never
    # called, so no model init / compile is needed — this benchmark
    # starts no device work at all
    params = {}
    MockedEngine = mocked(ServeEngine)
    MockedTemporal = mocked(TemporalServeEngine)

    def cfg(**kw):
        # cache DISABLED in the timed legs: a hit would short-circuit
        # admission and the leg would price the cache, not the submit
        # path; max_batch / max_delay defer every flush past the
        # measured submit window. Parity legs re-enable pieces via kw.
        base = dict(max_batch=MAX_BATCH, max_delay_ms=1e9, cache_entries=0)
        base.update(kw)
        return ServeConfig(**base)

    def node_engine(**kw):
        s = GraphSageSampler(topo, sizes=SIZES, mode="TPU", seed=SEED)
        eng = MockedEngine(model, params, s, feat, cfg(**kw))
        assert eng._programs is not None, "fused path required: a split " \
            "seal would run real sampling inside the measured window"
        return eng

    def temporal_engine(**kw):
        s = GraphSageSampler(topo, sizes=SIZES, mode="TPU", seed=SEED,
                             dedup=False, max_deg=128)
        ts = s.bind_temporal(TemporalTiledGraph(topo, base_ts), recency=0.02)
        eng = MockedTemporal(model, params, ts, feat, cfg(**kw),
                             t_quantum=4.0)
        assert eng._programs is not None
        return eng

    n = args.requests
    node_ids = zipfian_trace(N_NODES, n, alpha=0.99, seed=SEED)
    ttr = temporal_trace(N_NODES, n, alpha=0.99, seed=SEED, t0=60.0)
    ltr = lp_trace(topo, n // 2, seed=SEED)
    pair_ids = np.empty(2 * (n // 2), np.int64)
    pair_ids[0::2] = ltr.u
    pair_ids[1::2] = ltr.v

    # -- resolve bit-parity (always, --smoke included): node + temporal,
    # production shape (vector admission + indexed delivery), cache-fill
    # shape (put_many vs scalar put), and journal-on shape ---------------
    parity_pairs = [
        ("node/vector-admit", lambda: node_engine(record_dispatches=True),
         node_ids, None, False),
        ("node/cache-fill",
         lambda: node_engine(record_dispatches=True, cache_entries=512),
         node_ids, None, False),
        ("node/journal-on",
         lambda: node_engine(record_dispatches=True, journal_events=65536),
         node_ids, None, True),
        ("temporal/vector-admit",
         lambda: temporal_engine(record_dispatches=True),
         ttr.requests, ttr.t_query, False),
        ("temporal/cache-fill",
         lambda: temporal_engine(record_dispatches=True, cache_entries=512),
         ttr.requests, ttr.t_query, False),
    ]
    for label, make_pair, ids, ts, journal_on in parity_pairs:
        assert_resolve_parity(make_pair, ids, ts, label, journal_on)
    print(f"resolve bit-parity: {len(parity_pairs)} pairs OK "
          f"(logits + dispatch logs + cache + journal)", file=sys.stderr)

    traffic = {
        "node": (node_engine, node_ids, None),
        "temporal": (temporal_engine, ttr.requests, ttr.t_query),
        "pair": (node_engine, pair_ids, None),
    }

    legs = []
    for name, (make_eng, ids, ts) in traffic.items():
        for n_threads in (int(x) for x in args.threads.split(",")):
            best = {True: float("inf"), False: float("inf")}
            best_total = {True: float("inf"), False: float("inf")}
            phases = {True: None, False: None}
            for _ in range(args.repeats):
                # interleave scalar/batch so drift hits both paths alike
                for batched in (False, True):
                    eng = make_eng()
                    ph = drive(eng, ids, ts, n_threads, batched)
                    assert eng.stats.dispatches > 0
                    best[batched] = min(best[batched], ph["submit"])
                    if ph["total"] < best_total[batched]:
                        best_total[batched] = ph["total"]
                        phases[batched] = ph
            n_req = int(ids.shape[0])

            def us(x):
                return round(x / n_req * 1e6, 3)

            leg = {
                "traffic": name,
                "threads": n_threads,
                "requests": n_req,
                "submit_s_scalar": round(best[False], 6),
                "submit_s_batch": round(best[True], 6),
                "total_s_scalar": round(best_total[False], 6),
                "total_s_batch": round(best_total[True], 6),
                "requests_per_s_scalar": round(n_req / best[False], 1),
                "requests_per_s_batch": round(n_req / best[True], 1),
                "scalar_us_per_request": us(best[False]),
                "batch_us_per_request": us(best[True]),
                "batch_over_scalar": round(best[False] / best[True], 2),
                "total_requests_per_s_batch": round(
                    n_req / best_total[True], 1
                ),
                # per-phase split of the best-total repeat (round 22):
                # submit + drain walls, assemble/resolve busy from the
                # span recorder, deliver = results_many over the handles
                "phases_batch_us_per_request": {
                    "submit": us(phases[True]["submit"]),
                    "flush_assemble": us(phases[True]["assemble"]),
                    "resolve": us(phases[True]["resolve"]),
                    "deliver": us(phases[True]["deliver"]),
                    "drain_wall": us(
                        phases[True]["total"] - phases[True]["submit"]
                    ),
                },
                "phases_scalar_us_per_request": {
                    "submit": us(phases[False]["submit"]),
                    "flush_assemble": us(phases[False]["assemble"]),
                    "resolve": us(phases[False]["resolve"]),
                    "deliver": us(phases[False]["deliver"]),
                    "drain_wall": us(
                        phases[False]["total"] - phases[False]["submit"]
                    ),
                },
            }
            legs.append(leg)
            pb = leg["phases_batch_us_per_request"]
            print(
                f"{name} x{n_threads}: scalar "
                f"{leg['requests_per_s_scalar']:.0f}/s, batch "
                f"{leg['requests_per_s_batch']:.0f}/s submit "
                f"({leg['batch_over_scalar']:.1f}x) | batch total "
                f"{leg['total_requests_per_s_batch']:.0f}/s "
                f"[submit {pb['submit']:.2f} + assemble "
                f"{pb['flush_assemble']:.2f} + resolve {pb['resolve']:.2f} "
                f"+ deliver {pb['deliver']:.2f} us/req]",
                file=sys.stderr,
            )

    for leg in legs:
        assert leg["requests_per_s_batch"] >= leg["requests_per_s_scalar"], (
            f"batch submit slower than scalar on {leg['traffic']} "
            f"x{leg['threads']}: {leg}"
        )
        assert leg["total_s_batch"] <= leg["total_s_scalar"], (
            f"batch total (submit→drained) slower than scalar on "
            f"{leg['traffic']} x{leg['threads']}: {leg}"
        )
    best_ratio = max(leg["batch_over_scalar"] for leg in legs)
    if not args.smoke:
        assert best_ratio >= 10.0, (
            f"batch-vs-scalar best ratio {best_ratio:.1f}x < 10x: {legs}"
        )
    host_leg = next(
        leg for leg in legs if leg["traffic"] == "node" and leg["threads"] == 1
    )

    # -- total-path trajectory vs round 20 (non-smoke): the r02 bar ------
    r01_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "FRONTEND_r01.json",
    )
    total_vs_r01 = None
    if not args.smoke:
        with open(r01_path) as fh:
            r01 = json.load(fh)
        r01_leg = next(
            leg for leg in r01["legs"]
            if leg["traffic"] == "node" and leg["threads"] == 1
        )
        r01_us = r01_leg["total_s_batch"] / r01_leg["requests"] * 1e6
        r02_us = host_leg["total_s_batch"] / host_leg["requests"] * 1e6
        total_vs_r01 = round(r01_us / r02_us, 2)
        assert total_vs_r01 >= 3.0, (
            f"total-path (submit→drained) speedup vs FRONTEND_r01 is "
            f"{total_vs_r01:.2f}x < 3x ({r01_us:.3f} -> {r02_us:.3f} us/req)"
        )
        print(f"total-path vs r01 (node x1, batch): {total_vs_r01:.2f}x "
              f"({r01_us:.3f} -> {r02_us:.3f} us/req)", file=sys.stderr)

    pb = host_leg["phases_batch_us_per_request"]
    out = {
        "metric": "bench_frontend",
        "git_revision": git_revision(),
        "config": {
            "n_nodes": N_NODES,
            "requests": n,
            "repeats": args.repeats,
            "max_batch": MAX_BATCH,
            "mocked_device": True,
            "smoke": bool(args.smoke),
            "methodology": (
                "mocked _dispatch (canned read-only logits), cache "
                "disabled, flushes deferred past the timed submit phase "
                "(submit→drained reported as total, results_many timed "
                "separately as deliver), interleaved scalar/batch "
                "repeats, best-of-repeats per path; resolve bit-parity "
                "(logits + dispatch logs + cache + journal) asserted "
                "in-run against a _scalar_resolve twin on node and "
                "temporal traffic"
            ),
        },
        "legs": legs,
        "host_submit_us": host_leg["batch_us_per_request"],
        "host_submit_us_scalar": host_leg["scalar_us_per_request"],
        # drain wall per request (assemble+seal+mock-dispatch+resolve):
        # what scaling.serve_table(host_resolve_us=) prices
        "host_resolve_us": pb["drain_wall"],
        "host_deliver_us": pb["deliver"],
        "best_batch_over_scalar": best_ratio,
        "total_path_vs_r01": total_vs_r01,
        "asserts": {
            "batch_ge_scalar_all_legs": True,
            "batch_total_ge_scalar_total_all_legs": True,
            "resolve_bit_parity_node_and_temporal": True,
            "best_ratio_ge_10x": None if args.smoke else True,
            "total_path_ge_3x_r01": None if args.smoke else True,
        },
    }
    path = args.out
    if path is None and not args.smoke:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "FRONTEND_r02.json",
        )
    if path:
        with open(path, "w") as fh:
            json.dump(out, fh, indent=1)
            fh.write("\n")
        print(f"wrote {path}", file=sys.stderr)
    print(json.dumps({k: out[k] for k in
                      ("host_submit_us", "host_resolve_us",
                       "host_deliver_us", "best_batch_over_scalar",
                       "total_path_vs_r01")}))


if __name__ == "__main__":
    main()

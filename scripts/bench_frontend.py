"""Host-path frontend benchmark, round 20: scalar vs batch submit on a
MOCKED device.

Measures the submit→seal host cost of the serving engines with the
device leg stubbed out: the engine's `_dispatch` is overridden to return
canned read-only logits, so wall time IS host time (the TIER_r02
discipline: the thing being priced is isolated in-run, and scalar/batch
repeats are interleaved so machine drift hits both alike).

Two phases are timed per leg. The SUBMIT phase (admission: coalesce
probe, striped queue insert, rid draw, stats) is what the scalar-vs-
batch ratio and the canonical ``host_submit_us`` come from — flushes
are deferred past it (``max_batch`` larger than the trace, infinite
delay) so both paths pay identical seal cost outside the measured
window, and the cache is DISABLED so a hit cannot short-circuit the
path being priced. The DRAIN phase (assemble → seal → mocked dispatch →
resolve) is reported alongside as ``total``: the submit→seal cost of
the whole trace.

Legs: {node, temporal, pair} traffic x {scalar submit loop, one
`submit_many`} x {1, 4} submit threads. The pair leg drives LP endpoint
traffic (u,v interleaved) through the shared admission path — the
scoring head is device work and is mocked away with the rest.

Artifact: FRONTEND_r01.json with per-leg submit-phase requests/s +
ratio and the canonical ``host_submit_us`` (batch path, node traffic,
1 thread) that prices `scaling.serve_table(host_submit_us=)` via
``scripts/scaling_model.py --frontend``. Asserted in-run: every leg's
batch submit path >= its scalar path, and the best batch-vs-scalar
submit-throughput ratio >= 10x (the round-20 `_admit_chunk_fast`
vectorized admission carries it; --smoke runs a tiny trace and only
asserts batch >= scalar).
"""

import argparse
import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N_NODES = 1000
DIM = 16
SIZES = [4, 4]
OUT_DIM = 5
# flush-deferral bucket: larger than any default trace, so no inline
# fill-flush lands inside the measured submit window (with --requests
# above this, fills flush inline and the submit phase honestly includes
# them — the ratio assert still holds, with less margin)
MAX_BATCH = 4096
SEED = 7


def git_revision() -> str:
    try:
        return subprocess.check_output(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            text=True,
        ).strip()
    except Exception:
        return "unknown"


def make_graph():
    rng = np.random.default_rng(0)
    src = rng.integers(0, N_NODES, 6000)
    dst = rng.integers(0, N_NODES, 6000)
    keep = src != dst
    return np.stack([src[keep], dst[keep]])


def mocked(engine_cls):
    """Subclass an engine with the device leg stubbed: `_dispatch`
    returns canned read-only logits sized to the flush bucket. Seal
    still pads, draws the sampler key, and writes the dispatch log —
    the full host path runs; only the execute call is gone."""

    canned = np.zeros((MAX_BATCH, OUT_DIM), np.float32)
    canned.setflags(write=False)

    class Mocked(engine_cls):
        def _dispatch(self, fl):
            with self._lock:
                self.stats.dispatch_calls += 1
                self.stats.execute_calls += 1
            return canned

    Mocked.__name__ = f"Mocked{engine_cls.__name__}"
    return Mocked


def drain(eng):
    while eng._drainable():
        eng.flush()


def drive(eng, ids, ts, n_threads, batched):
    """Submit the whole trace (scalar loop or one submit_many per
    thread-chunk), then drain. Returns (submit_wall_s, total_wall_s):
    the submit phase is the admission cost the ratio assert prices;
    the drain (assemble → seal → mocked dispatch → resolve) is deferred
    past it by the flush-deferral config and identical for both
    paths."""
    chunk_ix = np.array_split(np.arange(ids.shape[0]), n_threads)
    errs = []

    def run(ix):
        try:
            if batched:
                if ts is None:
                    eng.submit_many(ids[ix])
                else:
                    eng.submit_many(ids[ix], t=ts[ix])
            elif ts is None:
                for i in ix:
                    eng.submit(int(ids[i]))
            else:
                for i in ix:
                    eng.submit(int(ids[i]), t=float(ts[i]))
        except Exception as exc:  # a failed leg must not record a time
            errs.append(exc)

    threads = [threading.Thread(target=run, args=(ix,)) for ix in chunk_ix]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    submit_wall = time.perf_counter() - t0
    drain(eng)
    total_wall = time.perf_counter() - t0
    if errs:
        raise errs[0]
    return submit_wall, total_wall


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=4000,
                    help="requests per measurement")
    ap.add_argument("--repeats", type=int, default=3,
                    help="interleaved scalar/batch repeats; best-of wins")
    ap.add_argument("--threads", default="1,4")
    ap.add_argument("--out", default=None,
                    help="artifact path (default FRONTEND_r01.json at the "
                         "repo root; --smoke writes nothing unless given)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny trace for CI: asserts batch >= scalar only")
    args = ap.parse_args()
    if args.smoke:
        args.requests = min(args.requests, 600)
        args.repeats = min(args.repeats, 2)

    from quiver_tpu import CSRTopo
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.pyg.sage_sampler import GraphSageSampler
    from quiver_tpu.serve import ServeConfig, ServeEngine
    from quiver_tpu.serve.trace_gen import lp_trace, temporal_trace, zipfian_trace
    from quiver_tpu.workloads import TemporalServeEngine, TemporalTiledGraph

    topo = CSRTopo(edge_index=make_graph())
    base_ts = np.random.default_rng(11).uniform(
        0.0, 50.0, topo.indices.shape[0]
    ).astype(np.float32)
    feat = np.zeros((N_NODES, DIM), np.float32)
    model = GraphSAGE(hidden_dim=16, out_dim=OUT_DIM, num_layers=2,
                      dropout=0.0)
    # the mocked `_dispatch` never touches params and `warmup` is never
    # called, so no model init / compile is needed — this benchmark
    # starts no device work at all
    params = {}
    MockedEngine = mocked(ServeEngine)
    MockedTemporal = mocked(TemporalServeEngine)

    def cfg():
        # cache DISABLED: a hit would short-circuit admission and the
        # leg would price the cache, not the submit path; max_batch /
        # max_delay defer every flush past the measured submit window
        return ServeConfig(max_batch=MAX_BATCH, max_delay_ms=1e9,
                           cache_entries=0)

    def node_engine():
        s = GraphSageSampler(topo, sizes=SIZES, mode="TPU", seed=SEED)
        eng = MockedEngine(model, params, s, feat, cfg())
        assert eng._programs is not None, "fused path required: a split " \
            "seal would run real sampling inside the measured window"
        return eng

    def temporal_engine():
        s = GraphSageSampler(topo, sizes=SIZES, mode="TPU", seed=SEED,
                             dedup=False, max_deg=128)
        ts = s.bind_temporal(TemporalTiledGraph(topo, base_ts), recency=0.02)
        eng = MockedTemporal(model, params, ts, feat, cfg(), t_quantum=4.0)
        assert eng._programs is not None
        return eng

    n = args.requests
    node_ids = zipfian_trace(N_NODES, n, alpha=0.99, seed=SEED)
    ttr = temporal_trace(N_NODES, n, alpha=0.99, seed=SEED, t0=60.0)
    ltr = lp_trace(topo, n // 2, seed=SEED)
    pair_ids = np.empty(2 * (n // 2), np.int64)
    pair_ids[0::2] = ltr.u
    pair_ids[1::2] = ltr.v

    traffic = {
        "node": (node_engine, node_ids, None),
        "temporal": (temporal_engine, ttr.requests, ttr.t_query),
        "pair": (node_engine, pair_ids, None),
    }

    legs = []
    for name, (make_eng, ids, ts) in traffic.items():
        for n_threads in (int(x) for x in args.threads.split(",")):
            best = {True: float("inf"), False: float("inf")}
            best_total = {True: float("inf"), False: float("inf")}
            for _ in range(args.repeats):
                # interleave scalar/batch so drift hits both paths alike
                for batched in (False, True):
                    eng = make_eng()
                    submit_wall, total_wall = drive(
                        eng, ids, ts, n_threads, batched
                    )
                    assert eng.stats.dispatches > 0
                    best[batched] = min(best[batched], submit_wall)
                    best_total[batched] = min(best_total[batched], total_wall)
            n_req = int(ids.shape[0])
            leg = {
                "traffic": name,
                "threads": n_threads,
                "requests": n_req,
                "submit_s_scalar": round(best[False], 6),
                "submit_s_batch": round(best[True], 6),
                "total_s_scalar": round(best_total[False], 6),
                "total_s_batch": round(best_total[True], 6),
                "requests_per_s_scalar": round(n_req / best[False], 1),
                "requests_per_s_batch": round(n_req / best[True], 1),
                "scalar_us_per_request": round(best[False] / n_req * 1e6, 3),
                "batch_us_per_request": round(best[True] / n_req * 1e6, 3),
                "batch_over_scalar": round(best[False] / best[True], 2),
            }
            legs.append(leg)
            print(
                f"{name} x{n_threads}: scalar "
                f"{leg['requests_per_s_scalar']:.0f}/s "
                f"({leg['scalar_us_per_request']:.1f} us/req), batch "
                f"{leg['requests_per_s_batch']:.0f}/s "
                f"({leg['batch_us_per_request']:.1f} us/req) -> "
                f"{leg['batch_over_scalar']:.1f}x submit-path",
                file=sys.stderr,
            )

    for leg in legs:
        assert leg["requests_per_s_batch"] >= leg["requests_per_s_scalar"], (
            f"batch path slower than scalar on {leg['traffic']} "
            f"x{leg['threads']}: {leg}"
        )
    best_ratio = max(leg["batch_over_scalar"] for leg in legs)
    if not args.smoke:
        assert best_ratio >= 10.0, (
            f"batch-vs-scalar best ratio {best_ratio:.1f}x < 10x: {legs}"
        )
    host_leg = next(
        leg for leg in legs if leg["traffic"] == "node" and leg["threads"] == 1
    )
    out = {
        "metric": "bench_frontend",
        "git_revision": git_revision(),
        "config": {
            "n_nodes": N_NODES,
            "requests": n,
            "repeats": args.repeats,
            "max_batch": MAX_BATCH,
            "mocked_device": True,
            "smoke": bool(args.smoke),
            "methodology": (
                "mocked _dispatch (canned read-only logits), cache "
                "disabled, flushes deferred past the timed submit phase "
                "(drain reported as total), interleaved scalar/batch "
                "repeats, best-of-repeats per path"
            ),
        },
        "legs": legs,
        "host_submit_us": host_leg["batch_us_per_request"],
        "host_submit_us_scalar": host_leg["scalar_us_per_request"],
        "best_batch_over_scalar": best_ratio,
        "asserts": {
            "batch_ge_scalar_all_legs": True,
            "best_ratio_ge_10x": None if args.smoke else True,
        },
    }
    path = args.out
    if path is None and not args.smoke:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "FRONTEND_r01.json",
        )
    if path:
        with open(path, "w") as fh:
            json.dump(out, fh, indent=1)
            fh.write("\n")
        print(f"wrote {path}", file=sys.stderr)
    print(json.dumps({k: out[k] for k in
                      ("host_submit_us", "best_batch_over_scalar")}))


if __name__ == "__main__":
    main()

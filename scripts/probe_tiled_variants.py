"""Probe 5: why the tiled fetch ties at hop-3 shape — formulation variants.

probe_tiled_sample: tiled ~= flat at (135168, 5) / (180224, 5) but wins
at smaller shapes, with 77-266 s compile times — the [B, k] -> [B,k,128]
3-D gather is not hitting the 145M rows/s path the 2-D [B] -> [B,128]
gather measured. Variants at B=135168, k=5, real FY positions excluded
(uniform random rows/lanes — fetch cost only):

  flat-elem   : element gather [B,k] from flat indices  (current wall)
  rows-3d     : take(tiles, rows[B,k], axis=0) -> [B,k,128], one-hot
  rows-2d     : take(tiles, rows.reshape(B*k), axis=0) -> [Bk,128], one-hot
  rows-2dT    : same but rows flattened TRANSPOSED (k-major), one-hot
  k-split     : k separate [B] -> [B,128] gathers (the measured-fast shape)
  fetch-only  : rows-2d without the select (isolate fetch vs select)
  sel-dot     : rows-2d + one-hot select via bf16 dot_general hmm int32 —
                via two 16-bit halves f32 dots

Run: python -u scripts/probe_tiled_variants.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

LANE = 128
B = 135_168
K = 5
ITERS = 100


def measure_rpc_floor(dev_x, n=6):
    ts = []
    for _ in range(n):
        t0 = time.time()
        float(jnp.sum(dev_x[:8]))
        ts.append(time.time() - t0)
    return float(np.median(ts))


def main():
    from bench import build_graph

    indptr_np, indices_np = build_graph()
    E = len(indices_np)
    M = E // LANE
    indices = jnp.asarray(indices_np.astype(np.int32))
    tiles = indices[: M * LANE].reshape(M, LANE)
    tiles.block_until_ready()
    floor = measure_rpc_floor(tiles)
    print(f"rpc floor {floor:.3f}s", flush=True)

    def timed(run, args, label):
        t0 = time.time()
        out = int(np.asarray(run(*args, jax.random.key(5)))[0])
        compile_s = time.time() - t0
        t0 = time.time()
        out = int(np.asarray(run(*args, jax.random.key(6)))[0])
        dt = max(time.time() - t0 - floor, 1e-9)
        print(
            f"{label:22s}: {dt*1e3/ITERS:7.2f} ms/iter  "
            f"(compile+first {compile_s:.1f}s, chk {out & 0xffff})",
            flush=True,
        )

    def scanned(body_fn):
        @jax.jit
        def run(flat_tab, tab, key0):
            def body(acc, i):
                kk = jax.random.fold_in(key0, i)
                return acc + body_fn(flat_tab, tab, kk), None

            acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(ITERS, dtype=jnp.int32))
            return jnp.stack([acc])

        return run

    def rand_rows(key):
        return jax.random.randint(key, (B, K), 0, M, jnp.int32)

    def rand_lanes(key):
        return jax.random.randint(key, (B, K), 0, LANE, jnp.int32)

    def onehot_sel(win_bkL, lane_bk):
        oh = lane_bk[..., None] == jnp.arange(LANE, dtype=jnp.int32)
        return jnp.where(oh, win_bkL, 0).sum(axis=-1)

    # flat-elem baseline
    def flat_elem(flat_tab, tab, kk):
        flat = jax.random.randint(kk, (B, K), 0, E, jnp.int32)
        got = jnp.take(flat_tab, flat)
        return got.sum(dtype=jnp.int32)

    timed(scanned(flat_elem), (indices, tiles), "flat-elem")

    def rows3d(flat_tab, tab, kk):
        k1, k2 = jax.random.split(kk)
        win = jnp.take(tab, rand_rows(k1), axis=0)  # [B,K,L]
        return onehot_sel(win, rand_lanes(k2)).sum(dtype=jnp.int32)

    timed(scanned(rows3d), (indices, tiles), "rows-3d+onehot")

    def rows2d(flat_tab, tab, kk):
        k1, k2 = jax.random.split(kk)
        win = jnp.take(tab, rand_rows(k1).reshape(-1), axis=0)  # [BK,L]
        sel = onehot_sel(win, rand_lanes(k2).reshape(-1))
        return sel.sum(dtype=jnp.int32)

    timed(scanned(rows2d), (indices, tiles), "rows-2d+onehot")

    def rows2dT(flat_tab, tab, kk):
        k1, k2 = jax.random.split(kk)
        win = jnp.take(tab, rand_rows(k1).T.reshape(-1), axis=0)
        sel = onehot_sel(win, rand_lanes(k2).T.reshape(-1))
        return sel.sum(dtype=jnp.int32)

    timed(scanned(rows2dT), (indices, tiles), "rows-2dT+onehot")

    def ksplit(flat_tab, tab, kk):
        k1, k2 = jax.random.split(kk)
        rows = rand_rows(k1)
        lanes = rand_lanes(k2)
        acc = jnp.int32(0)
        for j in range(K):
            win = jnp.take(tab, rows[:, j], axis=0)  # [B,L]
            oh = lanes[:, j][:, None] == jnp.arange(LANE, dtype=jnp.int32)[None, :]
            acc = acc + jnp.where(oh, win, 0).sum(dtype=jnp.int32)
        return acc

    timed(scanned(ksplit), (indices, tiles), "k-split+onehot")

    def fetch_only(flat_tab, tab, kk):
        k1, _ = jax.random.split(kk)
        win = jnp.take(tab, rand_rows(k1).reshape(-1), axis=0)
        return win.sum(dtype=jnp.int32)

    timed(scanned(fetch_only), (indices, tiles), "rows-2d fetch-only")

    def fetch_only_1d(flat_tab, tab, kk):
        k1, _ = jax.random.split(kk)
        rows = jax.random.randint(k1, (B * K,), 0, M, jnp.int32)
        win = jnp.take(tab, rows, axis=0)
        return win.sum(dtype=jnp.int32)

    timed(scanned(fetch_only_1d), (indices, tiles), "rows-1didx fetch-only")


if __name__ == "__main__":
    main()

"""Hermetic grouped-return-trip comparison: psum+slice vs psum_scatter.

Round-3 VERDICT item 4 asked for EVIDENCE (compiled-HLO collective bytes +
hermetic step time on the 8-device CPU mesh) deciding the grouped gather's
return trip. This script produces the SCALING.md round-4 table:

  - equality: both spellings produce identical rows;
  - compiled-HLO payload bytes per collective kind, per spelling, for the
    full sharded-topology train step on the (host=2, dp=2, ici=2) mesh;
  - byte-model prediction for both spellings (gather_comm_bytes /
    sampling_comm_bytes via=);
  - hermetic wall-clock per step (CPU mesh — relative, not absolute).

Run: QUIVER_VIRTUAL_DEVICES=8 python scripts/compare_grouped_return.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    from quiver_tpu.utils import force_virtual_cpu_devices

    force_virtual_cpu_devices(int(os.environ.get("QUIVER_VIRTUAL_DEVICES", "8")))

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from quiver_tpu import CSRTopo
    from quiver_tpu.datasets import synthetic_powerlaw
    from quiver_tpu.models import GraphSAGE
    from quiver_tpu.parallel import (
        make_mesh,
        make_sharded_topo_train_step,
        mesh_axes,
        replicate,
        shard_feature_rows,
        shard_topology_rows,
    )
    from quiver_tpu.parallel.scaling import collective_payload_bytes
    from quiver_tpu.parallel.topology import sampling_comm_bytes
    from quiver_tpu.pyg.sage_sampler import sample_dense_fused
    import quiver_tpu.parallel.collectives as coll
    import quiver_tpu.parallel.topology as topo_mod

    n, deg, dim, classes = 20_000, 10, 32, 8
    sizes, B = (8, 4), 64
    ei, feat, labels, train_idx = synthetic_powerlaw(
        n, n * deg, dim=dim, classes=classes, seed=0
    )
    topo = CSRTopo(edge_index=ei)
    mesh = make_mesh(8, hosts=2)
    data_axes, feat_axes, groups = mesh_axes(mesh)
    model = GraphSAGE(hidden_dim=32, out_dim=classes, num_layers=2, dropout=0.0)
    tx = optax.adam(1e-3)

    stopo = shard_topology_rows(mesh, topo)
    fd = shard_feature_rows(mesh, feat)
    ld = replicate(mesh, labels.astype(np.int32))
    seeds = jax.device_put(
        jnp.arange(B * groups, dtype=jnp.int32),
        NamedSharding(mesh, P(data_axes)),
    )
    ds0 = sample_dense_fused(
        jnp.asarray(topo.indptr.astype(np.int32)),
        jnp.asarray(topo.indices.astype(np.int32)),
        jax.random.key(0), jnp.arange(B, dtype=jnp.int32), sizes,
    )
    x0 = jnp.zeros((ds0.n_id.shape[0], dim), jnp.float32)
    params = replicate(mesh, model.init(jax.random.key(1), x0, ds0.adjs))
    opt = jax.device_put(tx.init(params), NamedSharding(mesh, P()))

    results = {}
    # patch the default `via` of both grouped collectives per spelling: the
    # step factories call them with the library default, so this flips the
    # WHOLE step (feature gathers + neighbor exchanges) in one move
    orig_g = coll.sharded_gather_grouped
    orig_s = topo_mod.sharded_sample_layer_grouped
    for via in ("psum", "scatter"):
        coll.sharded_gather_grouped = (
            lambda *a, _o=orig_g, _v=via, **k: _o(*a, **{**k, "via": _v})
        )
        topo_mod.sharded_sample_layer_grouped = (
            lambda *a, _o=orig_s, _v=via, **k: _o(*a, **{**k, "via": _v})
        )
        # train.py imported the symbols at module load: patch there too
        import quiver_tpu.parallel.train as train_mod

        train_mod.sharded_gather_grouped = coll.sharded_gather_grouped
        topo_mod_attr = getattr(train_mod, "sharded_sample_layer_grouped", None)
        step = make_sharded_topo_train_step(
            mesh, model, tx, sizes=sizes, pipeline="fused"
        )
        args = (params, opt, jax.random.key(2), stopo, fd, ld, seeds)
        compiled = step.lower(*args).compile()
        hlo = collective_payload_bytes(compiled.as_text())
        p, o, loss = compiled(*args)
        jax.block_until_ready(loss)
        t0 = time.time()
        for i in range(20):
            p, o, loss = compiled(*args)
        jax.block_until_ready(loss)
        dt = (time.time() - t0) / 20
        model_bytes = sampling_comm_bytes(
            mesh, sizes, B, feature_dim=dim, via=via
        )
        results[via] = dict(
            loss=float(loss), hlo=hlo, step_ms=dt * 1e3,
            model_ici=model_bytes["ici_bytes"], model_dcn=model_bytes["dcn_bytes"],
        )
    coll.sharded_gather_grouped = orig_g
    topo_mod.sharded_sample_layer_grouped = orig_s

    print(f"mesh {dict(mesh.shape)}, sizes {sizes}, batch/group {B}, dim {dim}")
    for via, r in results.items():
        hlo_total = sum(r["hlo"].values())
        print(
            f"  {via:8s}: step {r['step_ms']:.1f} ms | HLO payloads "
            f"{ {k: v for k, v in sorted(r['hlo'].items())} } (total {hlo_total}) "
            f"| model ici {r['model_ici']:.0f}B dcn {r['model_dcn']:.0f}B "
            f"| loss {r['loss']:.6f}"
        )
    same = abs(results["psum"]["loss"] - results["scatter"]["loss"]) < 1e-5
    print(f"  losses match: {same}")
    tot_p = sum(results["psum"]["hlo"].values())
    tot_s = sum(results["scatter"]["hlo"].values())
    print(f"  HLO collective bytes: scatter/psum = {tot_s/tot_p:.3f}")

    # --- a2a spelling: per-chip request lists (round-4 VERDICT item 7) ---
    # Three gathers over one flat 8-chip axis, W=512 global requests, D=32:
    #   repl : every chip holds the SAME W ids -> sharded_gather (the train
    #          steps' shape: the model consumes ALL W rows)
    #   a2a  : ids sharded W/P per chip -> each chip gets only ITS rows
    #   a2a+g: a2a followed by all_gather (apples-to-apples with repl)
    from quiver_tpu.parallel.train import _shard_map_fn as shard_map

    from quiver_tpu.parallel.collectives import (
        sharded_gather,
        sharded_gather_a2a,
    )

    flat = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("ici",))
    W, P_ = 512, 8
    table = jnp.asarray(
        np.arange(n * dim, dtype=np.float32).reshape(n, dim)[: (n // P_) * P_]
    )
    req = jnp.asarray(np.random.default_rng(3).integers(0, (n // P_) * P_, W))

    def run_case(name, fn, in_specs, out_specs, args):
        sm = jax.jit(
            shard_map(fn, mesh=flat, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False)
        )
        compiled = sm.lower(*args).compile()
        out = np.asarray(compiled(*args))
        hlo = collective_payload_bytes(compiled.as_text())
        print(f"  a2a-case {name:6s}: HLO payloads "
              f"{ {k: v for k, v in sorted(hlo.items())} } "
              f"(total {sum(hlo.values())})")
        return out

    got_repl = run_case(
        "repl",
        lambda tb, ids: sharded_gather(tb, ids, "ici"),
        (P("ici", None), P()), P(), (table, req),
    )
    got_a2a = run_case(
        "a2a",
        lambda tb, ids: sharded_gather_a2a(tb, ids, "ici", P_),
        (P("ici", None), P("ici")), P("ici"), (table, req),
    )
    got_a2ag = run_case(
        "a2a+g",
        lambda tb, ids: jax.lax.all_gather(
            sharded_gather_a2a(tb, ids, "ici", P_), "ici", tiled=True
        ),
        (P("ici", None), P("ici")), P(), (table, req),
    )
    expect = np.asarray(table)[np.asarray(req)]
    eq = (
        np.allclose(got_repl, expect)
        and np.allclose(got_a2a, expect)
        and np.allclose(got_a2ag, expect)
    )
    print(f"  a2a rows match replicated gather: {eq}")
    print("  decision: a2a halves the return-trip bytes ONLY while the"
          " consumer stays sharded; with full-row consumption (every train"
          " step here) the re-assembly all_gather pays it back — train"
          " steps keep sharded_gather/_grouped; a2a serves sharded"
          " consumers (docs/api.md).")


if __name__ == "__main__":
    main()

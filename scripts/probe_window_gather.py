"""Probe: contiguous slice-window gathers from the flat CSR indices array.

Question (NEXT.md round-4 idea 2a): the per-hop neighbor fetch is a
[B, k] ELEMENT gather — ~1 descriptor per element at the measured
~75-94M desc/s wall. A row's sampled positions all live in its edge
window [ptr, ptr+deg); if a contiguous slice gather of width w issues at
~1 descriptor per ROW (and stays descriptor-bound up to some width),
fetching each row's first-w edges as ONE slice and selecting sampled
lanes in-register would amplify the fetch rate by ~min(deg, k)x for all
rows with deg <= w.

Measures, with honest in-jit scan windows and floor correction:
  - element-gather baseline: [B*k] one-element takes from indices
  - slice-window gather:     [B, w] via vmap(dynamic_slice), w in
                             {2, 4, 8, 16, 32, 64, 128}
Reports descriptors/s and effective elems/s for each.

Run: python -u scripts/probe_window_gather.py  (TPU, nothing concurrent)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def measure_rpc_floor(dev_x, n=6):
    ts = []
    for _ in range(n):
        t0 = time.time()
        float(jnp.sum(dev_x[:8]))
        ts.append(time.time() - t0)
    return float(np.median(ts))


def main():
    sys.path.insert(0, "/root/repo")
    from bench import build_graph

    indptr_np, indices_np = build_graph()
    E = len(indices_np)
    print(f"graph: E={E}", flush=True)
    indices = jnp.asarray(indices_np.astype(np.int32))
    indices.block_until_ready()
    floor = measure_rpc_floor(indices)
    print(f"rpc floor {floor:.3f}s", flush=True)

    B = 180_224  # hop-3 frontier width in the e2e shapes
    K = 5

    def timed(run, key, iters, label, desc_per_iter, elem_per_iter):
        t0 = time.time()
        out = int(np.asarray(run(indices, key, jnp.int32(iters)))[0])
        compile_s = time.time() - t0
        t0 = time.time()
        out = int(np.asarray(run(indices, jax.random.fold_in(key, 7), jnp.int32(iters)))[0])
        dt = max(time.time() - t0 - floor, 1e-9)
        desc_rate = desc_per_iter * iters / dt
        elem_rate = elem_per_iter * iters / dt
        print(
            f"{label:24s}: {dt*1e3/iters:8.2f} ms/iter  "
            f"{desc_rate/1e6:8.1f}M desc/s  {elem_rate/1e6:8.1f}M elem/s  "
            f"(compile+first {compile_s:.1f}s, chk {out & 0xffff})",
            flush=True,
        )
        return dt / iters

    # --- element-gather baseline: B*K one-element takes -------------------
    def make_elem(iters_static_n=None):
        @jax.jit
        def run(ix, key0, iters):
            def body(acc, i):
                key = jax.random.fold_in(key0, i)
                flat = jax.random.randint(key, (B, K), 0, E, jnp.int32)
                got = jnp.take(ix, flat)
                return acc + got.sum(dtype=jnp.int32), None

            acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(200, dtype=jnp.int32))
            return jnp.stack([acc])

        return run

    timed(make_elem(), jax.random.key(0), 200, f"element [B,{K}]", B * K, B * K)

    # --- slice-window gathers --------------------------------------------
    for w in (2, 4, 8, 16, 32, 64, 128):
        iters = 200 if w <= 32 else 60

        def make_win(w=w, iters=iters):
            @jax.jit
            def run(ix, key0, _):
                def body(acc, i):
                    key = jax.random.fold_in(key0, i)
                    starts = jax.random.randint(key, (B,), 0, E - w, jnp.int32)
                    win = jax.vmap(
                        lambda p: lax.dynamic_slice(ix, (p,), (w,))
                    )(starts)
                    return acc + win.sum(dtype=jnp.int32), None

                acc, _ = lax.scan(
                    body, jnp.int32(0), jnp.arange(iters, dtype=jnp.int32)
                )
                return jnp.stack([acc])

            return run

        timed(make_win(), jax.random.key(1), iters, f"window [B,{w}]", B, B * w)

    # --- window + in-register lane select (the real candidate op) --------
    for w in (16, 32, 64):
        iters = 150

        def make_winsel(w=w, iters=iters):
            @jax.jit
            def run(ix, key0, _):
                def body(acc, i):
                    key = jax.random.fold_in(key0, i)
                    k1, k2 = jax.random.split(key)
                    starts = jax.random.randint(k1, (B,), 0, E - w, jnp.int32)
                    pos = jax.random.randint(k2, (B, K), 0, w, jnp.int32)
                    win = jax.vmap(
                        lambda p: lax.dynamic_slice(ix, (p,), (w,))
                    )(starts)
                    got = jnp.take_along_axis(win, pos, axis=1)
                    return acc + got.sum(dtype=jnp.int32), None

                acc, _ = lax.scan(
                    body, jnp.int32(0), jnp.arange(iters, dtype=jnp.int32)
                )
                return jnp.stack([acc])

            return run

        timed(
            make_winsel(), jax.random.key(2), iters,
            f"window+select [B,{w}]->{K}", B, B * K,
        )


if __name__ == "__main__":
    main()

"""Export an OGB node-property dataset to the quiver_tpu .npz interchange.

Run this anywhere the `ogb` package is installed (it is NOT required by
quiver_tpu itself); copy the resulting .npz next to the TPU job and point
the examples at it:

    python scripts/export_ogb.py --name ogbn-products --out products.npz
    python examples/reddit_sage.py --dataset products.npz --sizes 15,10,5

The export symmetrizes the edge list (products/reddit are undirected; the
reference samples the symmetrized CSR) and stores train/valid/test splits.
Format consumed by `quiver_tpu.datasets.load_npz`:
{edge_index [2,E] int64, features [N,D] float32, labels [N] int,
 train_idx, valid_idx, test_idx}.
"""

import argparse

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--name", default="ogbn-products")
    ap.add_argument("--root", default="dataset", help="ogb download dir")
    ap.add_argument("--out", required=True, help="output .npz path")
    ap.add_argument("--no-symmetrize", action="store_true")
    args = ap.parse_args()

    from ogb.nodeproppred import NodePropPredDataset  # external, not baked in

    ds = NodePropPredDataset(name=args.name, root=args.root)
    graph, labels = ds[0]
    split = ds.get_idx_split()

    edge_index = np.asarray(graph["edge_index"], dtype=np.int64)
    if not args.no_symmetrize:
        edge_index = np.concatenate([edge_index, edge_index[::-1]], axis=1)
    features = np.asarray(graph["node_feat"], dtype=np.float32)
    labels = np.asarray(labels).reshape(-1).astype(np.int32)

    import sys
    import os

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from quiver_tpu.datasets import save_npz

    save_npz(
        args.out,
        edge_index=edge_index,
        features=features,
        labels=labels,
        train_idx=np.asarray(split["train"], dtype=np.int64),
        valid_idx=np.asarray(split["valid"], dtype=np.int64),
        test_idx=np.asarray(split["test"], dtype=np.int64),
    )
    print(
        f"wrote {args.out}: {features.shape[0]} nodes, "
        f"{edge_index.shape[1]} edges, {features.shape[1]} dims, "
        f"{int(labels.max()) + 1} classes"
    )


if __name__ == "__main__":
    main()

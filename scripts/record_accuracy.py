"""Record the synthetic-graph accuracy anchors for round-over-round
regression visibility (VERDICT r2 item 6).

Real-dataset accuracy (reference anchor: ogbn-products GraphSAGE ~0.787,
dist_sampling_ogb_products_quiver.py:1) needs egress this image doesn't
have; `scripts/export_ogb.py` + `--dataset foo.npz` make that turnkey when
it does. Until then this trains the two example tasks hermetically and
writes ACCURACY.json at the repo root.

Usage: python scripts/record_accuracy.py  (CPU is fine; ~2-3 min)
"""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(script, args, env_extra=None):
    env = dict(os.environ)
    # hermetic CPU run regardless of any accelerator plugin in the parent
    # env (the axon tunnel backend is single-tenant and flaky under
    # contention; accuracy anchors don't need the chip)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ROOT
    if env_extra:
        env.update(env_extra)
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", script), *args],
        capture_output=True, text=True, env=env, timeout=900,
    )
    if out.returncode != 0:
        sys.stderr.write(out.stdout + "\n" + out.stderr + "\n")
        raise SystemExit(f"{script} failed with rc={out.returncode}")
    return out.stdout


def parse_accs(stdout):
    accs = {}
    for line in stdout.splitlines():
        # "val acc: 0.9470 (...)" / "test acc (full inference): 0.9470"
        if " acc" in line and ":" in line:
            name = line.split(":")[0].strip().replace(" ", "_").replace("(", "").replace(")", "")
            try:
                accs[name] = float(line.split(":")[1].strip().split()[0])
            except (ValueError, IndexError):
                pass
    return accs


def main():
    results = {}
    out = run_example(
        "reddit_sage.py",
        ["--epochs", "8", "--nodes", "20000", "--batch-size", "512", "--cache", "4M"],
    )
    results["reddit_sage_synthetic"] = parse_accs(out)
    out = run_example(
        "products_multichip.py",
        ["--epochs", "6", "--nodes", "20000", "--avg-deg", "10",
         "--steps-per-epoch", "20", "--batch-per-dp", "256", "--hidden", "64",
         "--classes", "8",
         # weaker class signal keeps the anchor off the 1.0 ceiling so a
         # regression can actually move it (round-3 verdict item 8)
         "--label-signal", "0.4"],
        env_extra={"QUIVER_VIRTUAL_DEVICES": "8"},
    )
    results["products_multichip_synthetic"] = parse_accs(out)
    path = os.path.join(ROOT, "ACCURACY.json")
    with open(path, "w") as fh:
        json.dump(results, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(json.dumps(results, indent=2, sort_keys=True))
    print(f"wrote {path}")


if __name__ == "__main__":
    main()

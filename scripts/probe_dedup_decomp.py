"""Probe 3: where does the standalone dedup (sample_dense_pure) iter go?

BENCH_r04: dedup 18.5M SEPS = 0.54x the 34.29M UVA baseline — the one
below-baseline row. Decompose an iter into sampling vs per-hop reindex,
uncapped vs capped, to size the levers (caps in the bench harness,
payload-slimmed sorts, fetch redesign) before building any.

Run: python -u scripts/probe_dedup_decomp.py   (TPU, nothing concurrent)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def measure_rpc_floor(dev_x, n=6):
    ts = []
    for _ in range(n):
        t0 = time.time()
        float(jnp.sum(dev_x[:8]))
        ts.append(time.time() - t0)
    return float(np.median(ts))


SIZES = (15, 10, 5)
CAPS = (16384, 135168, 499712)  # BENCH_r04 calibrated caps
B = 1024
ITERS = 60


def main():
    from bench import build_graph
    from quiver_tpu.pyg.sage_sampler import sample_dense_fused, sample_dense_pure
    from quiver_tpu.ops.reindex import local_reindex

    indptr_np, indices_np = build_graph()
    indptr = jnp.asarray(indptr_np)
    indices = jnp.asarray(indices_np.astype(np.int32))
    indices.block_until_ready()
    floor = measure_rpc_floor(indices)
    print(f"rpc floor {floor:.3f}s", flush=True)

    rng = np.random.default_rng(0)
    seeds_all = jnp.asarray(
        rng.integers(0, len(indptr_np) - 1, (24, B)).astype(np.int32)
    )

    def timed(fn, label, args):
        t0 = time.time()
        out = np.asarray(fn(*args, jax.random.key(5)))
        compile_s = time.time() - t0
        t0 = time.time()
        out = np.asarray(fn(*args, jax.random.key(6)))
        dt = max(time.time() - t0 - floor, 1e-9)
        edges = int(out[0]) if out.shape else 0
        per = dt * 1e3 / ITERS
        seps = edges / dt if edges > 0 else 0
        print(
            f"{label:34s}: {per:7.2f} ms/iter"
            + (f"  {seps/1e6:7.2f}M SEPS ({edges} edges)" if edges else "")
            + f"  (compile+first {compile_s:.1f}s)",
            flush=True,
        )
        return per

    def scanned(sample_fn, caps):
        @jax.jit
        def run(ip, ix, seeds, key0):
            def body(carry, i):
                acc, tacc = carry
                key = jax.random.fold_in(key0, i)
                if isinstance(caps, str):  # "NOCAPS" static sentinel
                    ds = sample_fn(ip, ix, key, seeds[i % 24], SIZES)
                else:
                    ds = sample_fn(ip, ix, key, seeds[i % 24], SIZES, caps)
                edges = sum(a.mask.sum(dtype=jnp.int32) for a in ds.adjs)
                touch = ds.n_id.sum(dtype=jnp.int32) + ds.count
                for a in ds.adjs:
                    if a.cols is not None:
                        touch = touch + a.cols.sum(dtype=jnp.int32)
                return (acc + edges, tacc + touch), None

            (acc, touch), _ = lax.scan(
                body, (jnp.int32(0), jnp.int32(0)),
                jnp.arange(ITERS, dtype=jnp.int32),
            )
            return jnp.stack([acc, touch])

        return run

    timed(scanned(sample_dense_fused, "NOCAPS"), "fused (ref point)", (indptr, indices, seeds_all))
    timed(scanned(sample_dense_pure, "NOCAPS"), "dedup uncapped (bench as-is)", (indptr, indices, seeds_all))
    timed(scanned(sample_dense_pure, CAPS), "dedup capped", (indptr, indices, seeds_all))

    # isolated hop-3-shaped reindex: W = 135168*6 = 811008
    S3 = CAPS[1]
    k3 = SIZES[2]

    @jax.jit
    def reindex_only(ip, ix, key0):
        seeds = jnp.arange(S3, dtype=jnp.int32) % (ip.shape[0] - 1)
        sv = jnp.ones((S3,), bool)

        def body(acc, i):
            key = jax.random.fold_in(key0, i)
            nbrs = jax.random.randint(key, (S3, k3), 0, ip.shape[0] - 1, jnp.int32)
            res = local_reindex(seeds, sv, nbrs, jnp.ones((S3, k3), bool))
            return acc + res.count + res.n_id.sum(dtype=jnp.int32) + res.local_nbrs.sum(dtype=jnp.int32), None

        acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(ITERS, dtype=jnp.int32))
        return jnp.stack([acc * 0])

    timed(reindex_only, "hop3-shape reindex only (811k)", (indptr, indices))

    # isolated hop-3-shaped SAMPLING only (capped frontier width)
    @jax.jit
    def sample3_only(ip, ix, key0):
        from quiver_tpu.ops.sample import sample_layer

        def body(acc, i):
            key = jax.random.fold_in(key0, i)
            cur = jax.random.randint(key, (S3,), 0, ip.shape[0] - 1, jnp.int32)
            nbrs, valid = sample_layer(ip, ix, cur, jnp.ones((S3,), bool), k3, key)
            return acc + nbrs.sum(dtype=jnp.int32) + valid.sum(dtype=jnp.int32), None

        acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(ITERS, dtype=jnp.int32))
        return jnp.stack([acc * 0])

    timed(sample3_only, "hop3-shape sampling only (135k,5)", (indptr, indices))

    # random-nbr variant of full reindex cost at hop-2 shape
    S2 = CAPS[0]
    k2 = SIZES[1]

    @jax.jit
    def reindex2_only(ip, ix, key0):
        seeds = jnp.arange(S2, dtype=jnp.int32) % (ip.shape[0] - 1)
        sv = jnp.ones((S2,), bool)

        def body(acc, i):
            key = jax.random.fold_in(key0, i)
            nbrs = jax.random.randint(key, (S2, k2), 0, ip.shape[0] - 1, jnp.int32)
            res = local_reindex(seeds, sv, nbrs, jnp.ones((S2, k2), bool))
            return acc + res.count + res.n_id.sum(dtype=jnp.int32), None

        acc, _ = lax.scan(body, jnp.int32(0), jnp.arange(ITERS, dtype=jnp.int32))
        return jnp.stack([acc * 0])

    timed(reindex2_only, "hop2-shape reindex only (180k)", (indptr, indices))


if __name__ == "__main__":
    main()

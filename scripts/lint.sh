#!/usr/bin/env bash
# Lint gate (reference scripts/lint.sh analog). This image ships no
# flake8/ruff (and installs are disallowed), so the gate is: every source
# byte-compiles, no syntax errors, no tabs-in-indentation, no merge
# markers, no stray breakpoints.
set -euo pipefail
cd "$(dirname "$0")/.."

python -m compileall -q quiver_tpu quiver tests examples scripts benchmarks bench.py __graft_entry__.py setup.py

fail=0
if grep -rn --include='*.py' -P '^\t' quiver_tpu quiver tests examples scripts; then
  echo "^ tabs in indentation"; fail=1
fi
if grep -rn --include='*.py' -E '^(<<<<<<<|=======$|>>>>>>>)' quiver_tpu quiver tests examples scripts; then
  echo "^ merge markers"; fail=1
fi
if grep -rn --include='*.py' -E 'breakpoint\(\)|pdb\.set_trace' quiver_tpu quiver examples scripts; then
  echo "^ stray debugger"; fail=1
fi
exit $fail

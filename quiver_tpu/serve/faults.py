"""Deterministic fault injection for the distributed serving fleet.

The round-15 fleet policies (hot-set replication, hedged/failover
dispatch, per-owner ejection) only earn their keep if they can be PROVEN
against failures — and a proof that depends on wall-clock races is no
proof at all. This module injects owner failures at chosen ROUTER
DISPATCH INDICES (the same monotonic index the dispatch log and sampler
key stream ride), so a faulty run is exactly as replayable as a healthy
one: run the same trace with the same `FaultInjector` plan twice and the
same owners fail at the same flushes, the same sub-batches hedge to the
same targets, and every completed request's logits are bit-identical.

Fault kinds:

- ``"kill"``  — the owner is DEAD from the given dispatch index onward:
  every routed sub-batch to it raises :class:`OwnerKilled` until the end
  of the run (the machine-went-away case; drives ejection/backoff).
- ``"error"`` — the owner raises :class:`OwnerFault` at exactly the
  given dispatch index, then recovers (the transient-crash case; drives
  hedge-and-retry without ejection when ``eject_after`` > 1).
- ``"stall"`` — the owner leg sleeps ``stall_s`` seconds at the given
  dispatch index before serving normally (the slow-host case; drives the
  ``hedge_deadline_ms`` timeout path — inherently wall-clock, so stall
  tests pin ORACLE parity, not cross-run bit-equality of who served).

The injector sits on the ROUTER side of the owner call
(`DistServeEngine._dispatch`, ``exchange="host"`` mode — the per-owner
legs are individually addressable there; the collective exchange is one
launch and cannot fail per-owner). It never touches engine state: a
fault is an exception the hedging machinery handles like any real owner
failure, which is exactly the point — the tested path IS the production
path.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class OwnerFault(RuntimeError):
    """Injected transient owner failure (one dispatch index)."""


class OwnerKilled(OwnerFault):
    """Injected permanent owner death (every dispatch index >= fid)."""


@dataclass(frozen=True)
class FaultSpec:
    """One planned fault: ``owner`` fails at router dispatch index
    ``fid`` with the given ``kind`` ("kill" | "error" | "stall");
    ``stall_s`` is the injected delay for stalls.

    ``at`` chooses the index space ``fid`` lives in: ``"dispatch"``
    (default, the round-15 behavior — router dispatch indices) or
    ``"migration"`` (round 16) — ``fid`` is then a MIGRATION BATCH index
    (`DistServeEngine.scale`/`rebalance` count handoff batches
    monotonically, exactly like the dispatch index counts flushes), and
    the fault fires inside `check_migration` at the range-handoff points
    the migration machinery defines: a killed DESTINATION rolls the
    in-flight range back to the old owner, a killed SOURCE rolls it
    forward to the new one — deterministically, because the decision
    reads only (owner, batch index). A migration ``kill`` also leaves
    the owner DEAD for every later serve dispatch (the machine is gone,
    not just the migration)."""

    owner: int
    fid: int
    kind: str
    stall_s: float = 0.0
    at: str = "dispatch"

    def __post_init__(self):
        if self.kind not in ("kill", "error", "stall"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.at not in ("dispatch", "migration"):
            raise ValueError(f"unknown fault site {self.at!r}")
        if self.at == "dispatch" and self.fid < 1:
            raise ValueError("fid is a dispatch index (first flush seals 1)")
        if self.at == "migration" and self.fid < 0:
            raise ValueError("fid is a migration batch index (first is 0)")
        if self.kind == "stall" and self.stall_s <= 0:
            raise ValueError("stall faults need stall_s > 0")


class FaultInjector:
    """Deterministic, replayable owner-fault schedule.

    ``check(owner, fid)`` is the router's hook, called once per routed
    owner sub-batch BEFORE the owner engine runs: it raises/sleeps per
    the plan and records what fired into ``log`` (``(fid, owner, kind)``
    tuples; read `events()` for the sorted view — concurrent in-flight
    flushes may append out of dispatch order). Keyed purely by
    (owner, dispatch index): no wall time, no randomness at check time,
    so a replayed run fires identically.

    Round 23: the router fans owner legs out onto per-flush worker
    threads, so `check` now fires CONCURRENTLY across the legs of one
    flush — at exactly the same (owner, fid) points as the sequential
    pass (each leg carries its own hook; the plan lookup is read-only
    and log appends are locked). Only the raw ``log`` APPEND ORDER can
    differ between the two schedulers; `events()` is the comparison
    view either way, and a "stall" sleep on a leg thread releases the
    GIL — a stalled owner overlaps the other legs instead of stalling
    the flush, which is what the fan-out exists to buy.
    """

    def __init__(self, faults: Sequence[FaultSpec] = ()):
        self.faults: Tuple[FaultSpec, ...] = tuple(faults)
        self._kill_at: Dict[int, int] = {}
        self._oneshot: Dict[Tuple[int, int], FaultSpec] = {}
        # migration-indexed plan (FaultSpec.at == "migration"): kills by
        # first dead batch index, one-shots by (owner, batch index)
        self._mig_kill_at: Dict[int, int] = {}
        self._mig_oneshot: Dict[Tuple[int, int], FaultSpec] = {}
        # owners a migration kill has ALREADY fired for: dead for every
        # serve dispatch from that point on (guarded by _lock)
        self._dead_owners: set = set()
        for f in self.faults:
            if f.at == "migration":
                if f.kind == "kill":
                    prev = self._mig_kill_at.get(f.owner)
                    self._mig_kill_at[f.owner] = (
                        f.fid if prev is None else min(prev, f.fid)
                    )
                else:
                    self._mig_oneshot[(f.owner, f.fid)] = f
            elif f.kind == "kill":
                prev = self._kill_at.get(f.owner)
                self._kill_at[f.owner] = f.fid if prev is None else min(prev, f.fid)
            else:
                self._oneshot[(f.owner, f.fid)] = f
        self._lock = threading.Lock()
        self.log: List[Tuple[int, int, str]] = []
        self.mig_log: List[Tuple[int, int, str]] = []

    @classmethod
    def seeded(
        cls,
        owners: Sequence[int],
        n_faults: int,
        seed: int,
        fid_range: Tuple[int, int] = (2, 32),
        kinds: Sequence[str] = ("error",),
        stall_s: float = 0.05,
    ) -> "FaultInjector":
        """A random-but-deterministic plan: ``n_faults`` specs drawn from
        ``seed`` over the given owners / dispatch-index range / kinds.
        Same seed, same plan — the probe's sweep legs ride this."""
        rng = np.random.default_rng(seed)
        owners = list(owners)
        lo, hi = fid_range
        specs = [
            FaultSpec(
                owner=int(owners[int(rng.integers(0, len(owners)))]),
                fid=int(rng.integers(lo, hi)),
                kind=str(kinds[int(rng.integers(0, len(kinds)))]),
                stall_s=stall_s,
            )
            for _ in range(n_faults)
        ]
        return cls(specs)

    def check(self, owner: int, fid: int) -> None:
        """Fire any fault planned for (owner, fid). Raises
        `OwnerKilled`/`OwnerFault` or sleeps (stall), recording every
        firing; a no-fault pair returns immediately."""
        owner, fid = int(owner), int(fid)
        with self._lock:
            mig_dead = owner in self._dead_owners
        if mig_dead:
            # killed by a migration-indexed fault: the machine is gone,
            # so every serve dispatch to it fails from that point on
            with self._lock:
                self.log.append((fid, owner, "kill"))
            raise OwnerKilled(
                f"owner {owner} killed mid-migration (serve dispatch "
                f"index {fid})"
            )
        kill_fid = self._kill_at.get(owner)
        if kill_fid is not None and fid >= kill_fid:
            with self._lock:
                self.log.append((fid, owner, "kill"))
            raise OwnerKilled(
                f"owner {owner} killed at dispatch index {kill_fid} "
                f"(now {fid})"
            )
        spec = self._oneshot.get((owner, fid))
        if spec is None:
            return
        with self._lock:
            self.log.append((fid, owner, spec.kind))
        if spec.kind == "error":
            raise OwnerFault(
                f"owner {owner} injected error at dispatch index {fid}"
            )
        time.sleep(spec.stall_s)  # "stall": delay, then serve normally

    def check_migration(self, owner: int, mig: int) -> None:
        """The migration-side hook (round 16): fire any fault planned for
        ``owner`` at MIGRATION batch index ``mig``. Called by
        `DistServeEngine._migrate_batch` once per participant (destination
        while its shard lands, source after) — a raised `OwnerKilled`
        there rolls the in-flight range back (dst dead) or forward (src
        dead). A migration kill also marks the owner dead for every later
        `check` (serve dispatches), because the machine — not the
        migration — failed. Keyed purely by (owner, batch index):
        replayable by construction, like `check`."""
        owner, mig = int(owner), int(mig)
        kill_mig = self._mig_kill_at.get(owner)
        if kill_mig is not None and mig >= kill_mig:
            with self._lock:
                self.mig_log.append((mig, owner, "kill"))
                self._dead_owners.add(owner)
            raise OwnerKilled(
                f"owner {owner} killed at migration batch {kill_mig} "
                f"(now {mig})"
            )
        spec = self._mig_oneshot.get((owner, mig))
        if spec is None:
            return
        with self._lock:
            self.mig_log.append((mig, owner, spec.kind))
        if spec.kind == "error":
            raise OwnerFault(
                f"owner {owner} injected error at migration batch {mig}"
            )
        time.sleep(spec.stall_s)  # "stall": delay the handoff, then land

    def migration_events(self) -> List[Tuple[int, int, str]]:
        """Fired migration faults sorted by (batch index, owner, kind) —
        the replay-comparison view of the migration plan."""
        with self._lock:
            return sorted(self.mig_log)

    def events(self) -> List[Tuple[int, int, str]]:
        """Fired faults sorted by (fid, owner, kind) — the deterministic
        view replay comparisons read (append order may interleave across
        concurrent in-flight flushes)."""
        with self._lock:
            return sorted(self.log)

    def killed_owners(self) -> Dict[int, int]:
        """{owner: first dead dispatch index} for kill specs."""
        return dict(self._kill_at)

    def clear_log(self) -> None:
        with self._lock:
            self.log.clear()
            self.mig_log.clear()

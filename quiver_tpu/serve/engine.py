"""Online serving engine: dynamic micro-batching, request coalescing, a
params-versioned embedding cache, and pipelined dispatch.

`inference.sampled_eval` is an OFFLINE loop: it owns its batch composition
and pays one sample + gather + forward per 1024 seeds. Online traffic
inverts every assumption — requests arrive one at a time, skewed toward hot
nodes, and each caller wants ONE row of logits at low latency. Paying a
full dispatch per request would burn the whole device budget on padding;
this engine turns the request stream back into efficient fixed-shape device
work with three levers, applied in order of cheapness:

1. **Embedding cache** (:class:`quiver_tpu.serve.cache.EmbeddingCache`):
   repeat requests for a node already computed under the CURRENT
   ``params_version`` are answered from host memory — no device work at
   all. `update_params` bumps the version and invalidates, so a served
   result may be cache-aged but never crosses a weight update.
2. **Cross-request coalescing**: within a flush window, identical seed ids
   collapse to ONE slot — 50 concurrent callers asking for the same hot
   node cost one sample/gather/forward and share the result. Requests
   arriving while that node is in flight attach to the in-flight slot.
3. **Dynamic micro-batching**: cache-missing unique seeds queue until
   ``max_batch`` are waiting or the oldest has aged ``max_delay_ms``, then
   flush as one batch padded to a fixed BUCKET size (powers of two up to
   ``max_batch`` by default). Fixed buckets mean one compiled program per
   bucket serves all traffic — no per-request recompiles, ever.

The device path comes in two BIT-IDENTICAL flavors. The **fused
one-dispatch path** (round 11, the default wherever the sampler/feature
pair supports it — see ``ServeConfig.dispatch_mode``) runs
sample + gather + forward as ONE pre-bound AOT executable per bucket
(`inference.make_serve_step` / `inference.BucketPrograms`): a flush costs
one execute call, `warmup()` compiles-and-seals the program table so a
retrace after warmup is structurally impossible (miss = hard error), and
the per-flush seed buffer is donated. The **split path** is the exact
`sampled_eval` inner step split in two (`inference.sample_batch` +
`inference.forward_logits` == `inference.batch_logits`) and survives for
offline eval, cost attribution, and features that must gather host-side
(tiered `Feature`). Both consume the same sampler key stream, which is
what makes served logits BIT-IDENTICAL to offline eval on the same
(sampler state, batch) pair; the parity tests replay the engine's dispatch
log through a fresh sampler and compare exactly (tests/test_serve.py).

**Pipelined dispatch (round 9) + late admission (round 11).** A flush runs
three stages:

- **assemble** — drain up to ``max_batch`` pending slots and fix the
  bucket; then, with the drained flush PUBLISHED for late admission,
  take an in-flight window permit (while the flush waits for a slot,
  `submit` keeps admitting new seeds into its pad lanes — continuous
  seed-level batching, recovering slack that round 8–10 computed and
  discarded); finally SEAL: close admission, draw the monotonic dispatch
  index, append the dispatch-log entry, and consume the sampler's next
  key. The whole stage is serialized under a small sequencing lock, so the
  sampler's key stream and the replay log are identical IN DISPATCH ORDER
  no matter how many flushes are in flight or how admissions interleave
  (``dispatch_log[i]`` is the i-th seal and consumed the sampler's i-th
  call — the determinism contract the parity replay rides).
- **dispatch** — the device work + the blocking D2H: one pre-bound
  execute on the fused path, `forward_logits` on the split path. Runs
  OUTSIDE the sequencing lock, so the next flush assembles (and the host
  batches/coalesces) while the device executes this one.
- **resolve** — unpad, cache writeback (version-checked), per-flush slot
  resolution, latency/stat accounting. Completions may land out of
  dispatch order; each flush resolves only its OWN slots, so ordering
  never leaks into results.

``ServeConfig.max_in_flight`` bounds how many flushes may sit between
assemble and resolve at once (a semaphore window). `flush()` itself stays
fully synchronous — a lone caller thread behaves exactly like the round-8
serial engine, and ``max_in_flight=1`` reproduces it bit-for-bit even under
thread races. Overlap comes from CONCURRENT flush callers: submit-filled
inline flushes on client threads, and `start()`'s ``max_in_flight`` poller
threads. Per-stage spans land in ``stats.spans``
(:class:`quiver_tpu.trace.SpanRecorder`), so measured overlap is reported
the same honest way the tiered training pipeline reports it
(``overlap_frac`` = fraction of wall with >= 2 stages active).

`update_params` FENCES: it blocks new assembles, drains every in-flight
flush, then swaps the weights and bumps the version — so no served logit is
ever computed from a params tree that changed under it mid-flush, and no
two in-flight flushes ever straddle a version (which also keeps the
in-flight coalescing map collision-free). `warmup()` pre-binds every
bucket's executable (fused: AOT lower+compile, zero keys consumed, then
SEALED — a later miss is a hard error; split: one warm dispatch through a
twin sampler where supported) so first-request latency doesn't eat a
compile.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import operator
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..inference import (
    BucketPrograms,
    _cached_apply,
    draw_sample_key,
    forward_logits,
    pad_seed_batch,
    sample_batch,
)
from ..trace import (
    NULL_JOURNAL,
    EventJournal,
    HitRateCounter,
    LatencyHistogram,
    MetricsRegistry,
    SpanRecorder,
    WorkloadConfig,
    WorkloadMonitor,
    export_chrome_trace as _export_chrome_trace,
    register_hit_rate,
)
from .cache import EmbeddingCache


DEFAULT_TENANT = "default"


class ShedError(RuntimeError):
    """Request refused at admission: the engine's queue-depth bound was
    hit and the submitting tenant is at or over its weighted quota
    (``ServeConfig.max_queue_depth`` / ``tenant_weights``). Per-request
    and deterministic — the decision reads only queue state, never wall
    time — and delivered through the returned `ServeResult`, never
    raised out of ``submit`` itself."""


class DrainTimeout(RuntimeError):
    """``stop(drain=True)`` could not retire every queued request within
    ``ServeConfig.drain_deadline_s`` (e.g. a poller or owner died
    mid-flush). Undrained slots are resolved with this error so waiters
    unblock instead of hanging, and counted in ``stats.undrained``."""


def weighted_drain_keys(pending: Dict[int, "_Slot"], cap: int,
                        tenant_weights: Optional[Dict[str, float]],
                        ) -> List[int]:
    """A flush's drain set (caller holds the owning engine's lock): FIFO
    prefix of the pending queue, except when ``tenant_weights`` is set and
    the queue overflows ``cap`` — then each tenant gets its
    largest-remainder share of the flush (FIFO within a tenant), unused
    quota refills FIFO, and the picked keys keep their queue order so
    batch composition stays deterministic. Shared by `ServeEngine` and
    `DistServeEngine` so the two front ends make identical QoS
    decisions."""
    if not tenant_weights or len(pending) <= cap:
        return list(pending)[:cap]
    by_tenant: Dict[str, List[int]] = {}
    for k, slot in pending.items():
        by_tenant.setdefault(slot.tenant, []).append(k)
    tenants = sorted(by_tenant)
    weights = {t: float(tenant_weights.get(t, 1.0)) for t in tenants}
    total = sum(weights.values()) or 1.0
    shares = {t: cap * weights[t] / total for t in tenants}
    quota = {t: int(shares[t]) for t in tenants}
    rem = cap - sum(quota.values())
    for t in sorted(tenants, key=lambda t: (-(shares[t] - quota[t]), t))[:rem]:
        quota[t] += 1
    picked = set()
    for t in tenants:
        picked.update(by_tenant[t][: quota[t]])
    keys: List[int] = [k for k in pending if k in picked]
    if len(keys) < cap:  # a tenant under-filled its quota: FIFO refill
        for k in pending:
            if k not in picked:
                keys.append(k)
                if len(keys) == cap:
                    break
        order = {k: i for i, k in enumerate(pending)}
        keys.sort(key=order.__getitem__)
    return keys


def shed_decision(pending_len: int, tenant_pending: int, tenant: str,
                  max_queue_depth: int,
                  tenant_weights: Optional[Dict[str, float]]) -> bool:
    """The deterministic shed rule shared by both front ends: shed iff
    the pending queue is at ``max_queue_depth`` AND the tenant already
    holds its weighted share of it. A tenant under quota is admitted
    even at a full queue (the bound protects light tenants from heavy
    ones, not the queue from light tenants)."""
    if max_queue_depth <= 0 or pending_len < max_queue_depth:
        return False
    if not tenant_weights:
        return True  # single implicit tenant: plain depth bound
    w = float(tenant_weights.get(tenant, 1.0))
    total = sum(float(v) for v in tenant_weights.values())
    if tenant not in tenant_weights:
        total += w
    # all-zero weights (every tenant "blocked"): fall back to the plain
    # depth bound with a 1-slot floor per tenant — never divide by zero
    quota = max(1, int(max_queue_depth * w / (total or 1.0)))
    return tenant_pending >= quota


def resolve_tenants(tenant, n: int) -> List[str]:
    """Per-request tenant names from a None / scalar / aligned-sequence
    spelling — the one normalization behind every ``submit_many``
    (single-host, router, temporal), so batch tenant semantics can never
    drift between front ends."""
    if tenant is None:
        return [DEFAULT_TENANT] * n
    if isinstance(tenant, str):
        return [tenant] * n
    tenants = [DEFAULT_TENANT if x is None else str(x) for x in tenant]
    if len(tenants) != n:
        raise ValueError(f"tenants has {len(tenants)} entries for {n} ids")
    return tenants


class _PendingStripes:
    """Striped pending-queue state shared by both serve front ends
    (round 20): ``n`` insertion-ordered dicts (key -> `_Slot`), each under
    its own lock, so concurrent submit threads for keys in different
    stripes never serialize on one engine-wide lock. A global GIL-atomic
    arrival counter stamps every inserted slot (``_Slot.seq``), and every
    ordered view merges the stripes by it — so the merged queue IS the
    single-dict FIFO of rounds 8–19 bit for bit: `weighted_drain_keys`
    over the merged view, and therefore batch composition and the
    dispatch log, cannot tell the stripes exist.

    ``stripe_key`` maps a request key to a stable stripe hint — `hash`
    on the single-host engine, the BUILD-TIME owner partition on the
    router (per-owner pending queues; the hint must never move with live
    placement, or a coalesce probe could miss its own pending slot).

    LOCK HIERARCHY: stripe locks are taken BEFORE the engine's ``_lock``,
    never after. Admission holds ONE stripe lock (or `all_locks` on the
    batch path) and takes ``_lock`` only for the brief rid/late-admission
    window inside it; drain and fence paths (`_assemble`,
    ``update_params``, `abandon_undrained`) enter `all_locks` (ascending
    index) first and only then ``_lock``. ``*_unlocked`` accessors are
    for callers already inside `all_locks`. Per-tenant pending counts
    live per stripe and SUM on read — exact whenever the caller holds
    the relevant locks or a single thread submits (the determinism
    contract's cases); unlocked reads (`__len__`, metrics gauges) are
    GIL-consistent snapshots."""

    __slots__ = ("n", "locks", "maps", "tenants", "stripe_key", "_arrival")

    def __init__(self, n: int, stripe_key: Optional[Callable] = None):
        self.n = max(1, int(n))
        self.locks = tuple(threading.Lock() for _ in range(self.n))
        self.maps: Tuple[Dict, ...] = tuple({} for _ in range(self.n))
        self.tenants: Tuple[Dict[str, int], ...] = tuple(
            {} for _ in range(self.n)
        )
        self.stripe_key = stripe_key if stripe_key is not None else hash
        self._arrival = itertools.count()  # next() is GIL-atomic

    def stripe_of(self, key) -> int:
        return self.stripe_key(key) % self.n

    def lock_for(self, key) -> threading.Lock:
        return self.locks[self.stripe_of(key)]

    @contextlib.contextmanager
    def all_locks(self):
        for lk in self.locks:
            lk.acquire()
        try:
            yield
        finally:
            for lk in reversed(self.locks):
                lk.release()

    # -- unlocked views (GIL-consistent; exact under the locks) -----------

    def __len__(self) -> int:
        return sum(len(m) for m in self.maps)

    def __bool__(self) -> bool:
        return any(self.maps)

    def get(self, key):
        return self.maps[self.stripe_of(key)].get(key)

    def tenant_count(self, tenant: str) -> int:
        return sum(t.get(tenant, 0) for t in self.tenants)

    # -- mutations (caller holds the key's stripe lock / all_locks) -------

    def insert_unlocked(self, key, slot, tenant: str) -> None:
        s = self.stripe_of(key)
        slot.seq = next(self._arrival)
        self.maps[s][key] = slot
        t = self.tenants[s]
        t[tenant] = t.get(tenant, 0) + 1

    def pop_unlocked(self, key):
        s = self.stripe_of(key)
        slot = self.maps[s].pop(key)
        t = self.tenants[s]
        n = t.get(slot.tenant, 1) - 1
        if n > 0:
            t[slot.tenant] = n
        else:
            t.pop(slot.tenant, None)
        return slot

    def clear_unlocked(self) -> None:
        for m in self.maps:
            m.clear()
        for t in self.tenants:
            t.clear()

    def values_unlocked(self):
        for m in self.maps:
            yield from m.values()

    def ordered_items_unlocked(self) -> List[Tuple[object, "_Slot"]]:
        """(key, slot) pairs in global arrival order — the exact
        single-dict insertion order striping replaced."""
        items = [kv for m in self.maps for kv in m.items()]
        items.sort(key=lambda kv: kv[1].seq)
        return items

    def ordered_dict_unlocked(self) -> Dict:
        return dict(self.ordered_items_unlocked())

    # -- self-locking views (caller must NOT hold engine._lock) -----------

    def ordered_keys(self) -> List:
        with self.all_locks():
            return [k for k, _ in self.ordered_items_unlocked()]

    def oldest_enqueue_t(self) -> Optional[float]:
        """Enqueue time of the globally oldest pending slot (None when
        empty) — the flush-age policy input. Per stripe, the head of the
        insertion-ordered dict is that stripe's oldest; the global oldest
        is the min-seq head across stripes."""
        best = None
        best_seq = None
        for lk, m in zip(self.locks, self.maps):
            with lk:
                it = iter(m.values())
                head = next(it, None)
            if head is not None and (best_seq is None or head.seq < best_seq):
                best, best_seq = head.enqueue_t, head.seq
        return best


def tenant_latency_hist(tenant_latency: Dict[str, LatencyHistogram],
                        tenant: str) -> LatencyHistogram:
    """Get-or-create a tenant's latency histogram — the one creation
    path shared by `ServeStats.tenant_hist` and
    `DistServeStats.tenant_hist`, so the router's per-tenant tails can
    never diverge from the single-host engine's in construction."""
    h = tenant_latency.get(tenant)
    if h is None:
        h = tenant_latency[tenant] = LatencyHistogram()
    return h


def register_tenant_latency(reg, prefix: str, help_text: str, get_stats,
                            tenant_weights: Optional[Dict[str, float]],
                            labels: Optional[Dict[str, str]] = None) -> None:
    """Register the per-tenant latency histogram family (``tenant``
    label): tenants known from the QoS config plus any observed so far
    (later tenants appear on the next registration call). ``get_stats``
    is a zero-arg resolver so `reset_stats` swaps are followed. Shared
    by `ServeEngine.register_metrics` and the router."""
    for t in sorted(set(tenant_weights or ())
                    | set(get_stats().tenant_latency)):
        reg.histogram(
            f"{prefix}_tenant_latency_ms", help_text,
            dict(labels or {}, tenant=str(t)),
            fn=(lambda t=t: get_stats().tenant_latency.get(t)
                or LatencyHistogram()),
        )


def register_stream_reserve(reg, prefix: str, get_stream,
                            labels: Optional[Dict[str, str]] = None) -> None:
    """Expose a bound `stream.StreamingTiledGraph`'s `reserve_report()`
    as Prometheus gauges (round-19 satellite — the r18 leftover: reserve
    runway was only visible as a `StreamCapacityError` hard failure;
    these gauges make it an alertable curve). ``get_stream`` is a
    zero-arg resolver (None = not stream-bound, gauges are skipped), so
    the family follows rebinds. Shared by `ServeEngine.register_metrics`
    and the router's per-owner registration — one naming scheme
    fleet-wide. ``projected_commits_to_exhaustion`` exports -1 while no
    consumption has been observed (None in the report: nothing honest to
    project from)."""
    if get_stream() is None:
        return

    def field(name):
        def read(name=name):
            stream = get_stream()
            if stream is None:
                return 0
            v = stream.reserve_report()[name]
            return -1 if v is None else v

        return read

    reg.gauge_fn(f"{prefix}_stream_reserve_tiles", field("reserve_tiles"),
                 "spare tile rows planned for streaming appends", labels)
    reg.gauge_fn(f"{prefix}_stream_reserve_used", field("reserve_used"),
                 "reserve tile rows consumed by spills/installs", labels)
    reg.gauge_fn(f"{prefix}_stream_reserve_free", field("reserve_free"),
                 "reserve tile rows remaining", labels)
    reg.gauge_fn(f"{prefix}_stream_reserve_rows_per_commit",
                 field("rows_per_commit"),
                 "mean reserve rows consumed per delta commit", labels)
    reg.gauge_fn(f"{prefix}_stream_reserve_projected_commits",
                 field("projected_commits_to_exhaustion"),
                 "commits of runway left at the observed consumption "
                 "rate (-1 = no consumption observed yet)", labels)
    # round-21 lifecycle gauges: the compaction planner's inputs, so the
    # "is the working set actually flat" question is alertable
    reg.gauge_fn(f"{prefix}_stream_fragmented_lanes",
                 field("fragmented_lanes"),
                 "slack lanes inside held tile rows (spill growth + "
                 "deletions) — the compaction trim target", labels)
    reg.gauge_fn(f"{prefix}_stream_reclaimable_tiles",
                 field("reclaimable_tiles"),
                 "tile rows a compaction pass could reclaim now "
                 "(spill-retired ranges + trimmable tails)", labels)
    reg.gauge_fn(f"{prefix}_stream_dead_lane_frac",
                 field("dead_lane_frac"),
                 "expired (masked) lanes as a fraction of live lane "
                 "content — appends re-use these before consuming "
                 "reserve rows", labels)


def abandon_undrained(engine, drained: bool = True) -> None:
    """Resolve whatever a bounded ``stop`` left behind with
    `DrainTimeout` and count it in ``stats.undrained`` — shared by
    `ServeEngine` and `DistServeEngine` (both expose the queue state and
    stats fields this reads). ``drained`` distinguishes the message: a
    deliberate ``stop(drain=False)`` with queued work is not a deadline
    failure and must not read like one."""
    with engine._pending.all_locks(), engine._lock:
        leftover = len(engine._pending) + len(engine._inflight)
        if not leftover and not engine._inflight_flushes:
            return
        if drained:
            msg = (
                f"stop(drain=True) abandoned {leftover} slot(s) after "
                f"{engine.config.drain_deadline_s}s "
                f"({engine._inflight_flushes} flush(es) still in flight)"
            )
        else:
            msg = (
                f"stop(drain=False) left {leftover} queued slot(s) "
                f"unserved (no drain was requested)"
            )
        err = DrainTimeout(msg)
        for slot in list(engine._pending.values_unlocked()):
            slot.resolve(None, error=err)
        for slot in list(engine._inflight.values()):
            if not slot.resolved:
                slot.resolve(None, error=err)
        # clear BOTH maps: a later submit must never coalesce onto an
        # abandoned (errored) slot, and the wedged flush's eventual
        # _resolve skips already-set slots (resolve-once rule)
        engine._pending.clear_unlocked()
        engine._inflight.clear()
        engine.stats.undrained += leftover
        engine.stats.request_errors += leftover


def default_buckets(max_batch: int) -> Tuple[int, ...]:
    """Powers of two up to ``max_batch`` (inclusive, appended if it is not
    itself a power of two): the bucket ladder that bounds padding waste at
    2x while keeping the compiled-program count at ``log2(max_batch)``."""
    if max_batch < 1:
        raise ValueError("max_batch must be >= 1")
    out: List[int] = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(out)


@dataclass
class ServeConfig:
    """Engine knobs (see docs/api.md "Online serving").

    max_batch      : flush as soon as this many unique cache-missing seeds
                     are pending (also the largest bucket).
    max_delay_ms   : flush a non-empty queue once its OLDEST request has
                     waited this long — the latency/throughput trade knob.
    buckets        : fixed batch shapes; a flush pads up to the smallest
                     bucket >= its unique-seed count. Default: powers of
                     two up to ``max_batch``. One compiled program per
                     bucket actually used.
    max_in_flight  : bounded in-flight window — how many flushes may sit
                     between assemble and resolve at once. 1 reproduces the
                     round-8 serial engine bit-for-bit; 2 (default) lets
                     the host assemble/coalesce the next batch while the
                     device runs the current one. Overlap requires
                     concurrent flush callers (inline submit flushes,
                     `start()`'s pollers); `flush()` itself is synchronous.
    cache_entries  : embedding-cache capacity in rows (0 disables caching).
    clock          : injectable monotonic clock (seconds) — latency metrics,
                     stage spans, and the delay policy read ONLY this, so
                     tests drive flush timing deterministically with a fake
                     clock.
    flush_poll_ms  : background flusher poll period (`start()` mode only).
    record_dispatches : keep a log of (padded_batch, n_valid) per dispatch
                     for parity replay/debugging (off by default: it grows
                     with traffic). Log order == dispatch-index order ==
                     sampler key-stream order, even with in-flight > 1.
    dispatch_mode  : "auto" (default) serves through the FUSED one-program
                     path (`inference.make_serve_step` + AOT-pre-bound
                     `BucketPrograms`) whenever the sampler and feature
                     support it (TPU-mode sampler, dense in-jit-gatherable
                     feature — `inference.feature_gather_spec`), falling
                     back to the split sample/forward path otherwise
                     (tiered `Feature`, HOST/CPU samplers). "fused" makes
                     that fallback a construction-time error; "split"
                     forces the round-9 two-dispatch path (baselines,
                     features that must gather host-side). Fused and split
                     serve BIT-IDENTICAL logits on the same key stream.
    late_admission : admit seeds submitted AFTER a flush assembled into
                     that flush's pad lanes, up to its bucket, while it
                     waits for an in-flight window slot — continuous
                     seed-level batching: the pad slack was computed-and-
                     discarded waste, now it retires real requests.
                     Admission closes before the dispatch index and the
                     sampler key are drawn, so the dispatch log and key
                     stream stay deterministic and replayable
                     (``stats.late_admitted`` counts recovered lanes).
    journal_events : capacity of the request-lifecycle `trace.EventJournal`
                     (0 = disabled, the default). When on, the engine
                     stamps submit/coalesce/cache-hit/late-admit/assemble/
                     window-wait/dispatch/execute-done/resolve events on
                     its clock — `journal.request_breakdown()` then yields
                     per-stage p50/p99 and per-flush pad occupancy.
                     OBSERVE-ONLY: events never feed control flow, so
                     enabling it changes no served bit (pinned in
                     tests/test_obs.py); cost is one deque append per
                     event, cheap enough to leave on (bench.py
                     ``serve_obs_overhead_frac``).
    workload       : a `trace.WorkloadConfig` enables the round-13
                     workload telemetry (None = off, zero cost): a
                     `trace.WorkloadMonitor` taps every submitted seed
                     (frequency sketches), every `EmbeddingCache` get
                     outcome, per-flush width/latency, and — when the
                     feature is a tiered `Feature`/`QuantizedFeature` —
                     per-tier gather attribution. Decay ticks ride flush
                     SEALS (the dispatch index, under the sequencing
                     lock), never wall time, so sketch state is
                     replay-bit-stable. Same OBSERVE-ONLY contract as the
                     journal: enabling it changes no served bit (pinned
                     in tests/test_skew.py; measured price: bench.py
                     ``serve_skew_overhead_frac``).
                     ``engine.workload.skew_report()`` is the read side.
    tier_promote_batch : max row MOVES per adaptation pass (round 14;
                     bounds the apply batch's disk read + device
                     row-scatter, so a pass can never stall the fence
                     for long). Only read when the engine's feature has
                     an adaptive `tiers.TierStore` under it.
    tier_promote_min : minimum err-corrected sketch weight a row needs
                     to be CONSIDERED for promotion (the absolute floor
                     of the planner's hysteresis band — one-hit wonders
                     never buy a slot).
    tier_hysteresis : a candidate must beat its eviction victim's
                     estimate by this factor (keeps near-tied rows from
                     ping-ponging between adaptation passes).
    tier_adapt_every_s : background promote/demote consumer period in
                     seconds (`start()` spawns it when > 0 and the
                     feature is adaptive + workload telemetry is on;
                     0 = manual `adapt_tiers()` only — what the
                     deterministic tests drive). Placement application
                     is ALWAYS fenced like `update_params` regardless
                     of who calls it.
    tenant_weights : round-15 per-tenant admission: {tenant: weight}
                     flush-quota shares (None = no QoS, the pre-round-15
                     engine byte for byte). When the pending queue
                     exceeds ``max_batch``, `flush` drains tenants in
                     weighted proportion (largest-remainder apportioning,
                     FIFO within a tenant, unused quota refilled FIFO) —
                     a heavy tenant can saturate its share, never the
                     whole flush. Tenants absent from the dict weigh 1.0.
    max_queue_depth : queue-depth-bounded load shedding (0 = never shed).
                     A NEW request whose tenant is at/over its weighted
                     share of this bound while the queue is full is
                     refused with a `ShedError` carried in its
                     `ServeResult` (per-request, never engine-fatal).
                     The decision reads only queue state — deterministic
                     and logged (``ServeEngine.shed_log``). Cache hits
                     and coalesces never shed (they add no queue entry).
    drain_deadline_s : bound on ``stop(drain=True)``: if queued work
                     cannot be retired within this budget (a poller or
                     owner died mid-flush), remaining slots resolve with
                     `DrainTimeout` and are counted in
                     ``stats.undrained`` instead of hanging the caller.
    stream_invalidate_hops : round-17 streaming graphs — reverse-closure
                     depth of the delta cache invalidation (every cached
                     seed within this many hops of a changed row is
                     dropped at ``update_graph``). None (default) =
                     ``len(sampler.sizes) - 1``, the exact number of
                     EXPANSION hops: a changed row only alters a seed's
                     draws if the seed can expand it, and the final
                     hop's frontier is gathered but never expanded.
    stream_adapt_tiers : run one fenced `adapt_tiers` pass right after a
                     delta commit when the engine has an adaptive tier
                     store + workload telemetry (round-17 consumer (c):
                     a delta-hot subgraph pulls its rows off disk at the
                     commit, not at the next background timer tick).
                     False = timer/manual adaptation only.
    tier_prefetch  : round-18 flush-ahead prefetch (ROADMAP item 3a).
                     At assemble time the engine knows a flush's seed
                     set one window before dispatch — it walks the
                     EXPECTED k-hop closure (`tiers.expected_closure`
                     over the sampler's current graph) and issues
                     `AsyncReadPool` reads for the disk-resident rows,
                     so by the time the gather runs the bytes sit in
                     DRAM staging. STRICTLY OBSERVE-ONLY ON BITS:
                     staged rows are the same backing-file bytes the
                     direct read returns, no key is consumed, placement
                     never moves, and flush composition is untouched —
                     prefetch on/off serve bit-identical logits and
                     dispatch logs (pinned at mif 1/2 and hosts 1/2 in
                     tests/test_prefetch.py). Needs an adaptive
                     `tiers.TierStore` with a read pool under the
                     feature; silently inert otherwise. Counters:
                     ``stats.tier_prefetch_{issued,hit,wasted}``,
                     journal kinds ``prefetch_issue``/``prefetch_hit``.
    tier_prefetch_hops : closure depth of the prefetch walk. None
                     (default) = ``len(sampler.sizes)`` — the GATHERED
                     closure is one hop deeper than the expansion
                     closure (the round-11 closure-hops rule: the final
                     frontier is gathered, never expanded).
    tier_prefetch_max_rows : bound on closure rows walked AND rows
                     staged at once (BFS order, so truncation keeps the
                     nearest rows) — a super-hub seed can never turn
                     one flush's prefetch into a full-table scan.
    tier_prefetch_at : when the walk+issue runs. ``"submit"`` (default):
                     the submit that fills a bucket issues the pending
                     keys' closure reads BEFORE calling flush, so when
                     another flush is already in the dispatch path the
                     reads overlap that flush's ENTIRE service time —
                     genuinely one window before dispatch — and the
                     assemble-time pass only walks seeds the submit
                     batch missed (late admits, window flushes).
                     ``"assemble"``: walk only at assemble time (the
                     overlap is the window wait + sample stage). Both
                     spellings serve identical bits — the knob moves
                     WHEN reads are issued, never what is served.
    """

    max_batch: int = 64
    max_delay_ms: float = 2.0
    buckets: Optional[Sequence[int]] = None
    max_in_flight: int = 2
    cache_entries: int = 100_000
    clock: Callable[[], float] = time.monotonic
    flush_poll_ms: float = 0.2
    record_dispatches: bool = False
    dispatch_mode: str = "auto"
    late_admission: bool = True
    journal_events: int = 0
    workload: Optional[WorkloadConfig] = None
    tier_promote_batch: int = 64
    tier_promote_min: float = 2.0
    tier_hysteresis: float = 1.25
    tier_adapt_every_s: float = 0.0
    tenant_weights: Optional[Dict[str, float]] = None
    max_queue_depth: int = 0
    drain_deadline_s: float = 30.0
    stream_invalidate_hops: Optional[int] = None
    stream_adapt_tiers: bool = True
    tier_prefetch: bool = False
    tier_prefetch_hops: Optional[int] = None
    tier_prefetch_max_rows: int = 4096
    tier_prefetch_at: str = "submit"
    # round-20 vectorized host path: stripe count of the pending queue
    # (`_PendingStripes`) — concurrent submit threads for keys in
    # different stripes never share a lock. 1 reproduces the single-dict
    # engine's locking exactly; batch composition and dispatch logs are
    # stripe-count-invariant either way (arrival-order merge).
    submit_stripes: int = 8
    # round-21 graph lifecycle (`quiver_tpu.lifecycle`):
    # >0 = sliding-window TTL on a temporal stream — every update_graph
    # commit expires edges older than (max committed ts - window) under
    # the same fence, as masked ts->+inf lane writes (see
    # lifecycle.RetentionPolicy; window arithmetic on the f32 grid)
    stream_retention_window: float = 0.0
    # >0 = background compaction: a timer thread plans off-fence and
    # applies under the fence every this-many seconds, when at least
    # stream_compact_min_reclaim tile rows are reclaimable. Strictly
    # observe-only on bits (pinned).
    stream_compact_every_s: float = 0.0
    stream_compact_min_reclaim: int = 8
    stream_compact_max_moves: int = 0
    # >0 = auto re-provisioning: a commit that would raise
    # StreamCapacityError first grows the tile bank by this many rows
    # (one sealed-program rebuild via BucketPrograms.reprovision) and
    # retries once. 0 = capacity stays a planned hard error (r17).
    stream_provision_tiles: int = 0
    # round-23 wall-clock TTL daemon (the round-21 leftover): >0 = a
    # timer thread runs `expire_edges` every this-many seconds BETWEEN
    # commits, so a quiet stream's sliding window keeps expiring without
    # waiting for the next delta. Each pass is exactly a manual
    # `expire_edges` call — same update_graph fence, same version bumps,
    # same closure-exact cache invalidation. Off by default; start()
    # leaves it off unless retention is configured on a temporal
    # stream-bound sampler.
    stream_retention_every_s: float = 0.0
    # injectable wall-clock -> event-time map for the daemon: each pass
    # expires at ``cutoff_for(stream_retention_clock())``. None (the
    # default) keeps the deterministic commit-driven retention clock
    # where the last commit left it — a daemon pass then only re-applies
    # the last commit's cutoff (a catch-up, usually a no-op). Tests
    # inject a deterministic sequence here; production maps wall time to
    # stream event time.
    stream_retention_clock: Optional[Callable[[], float]] = None
    # round-24 zero-stall commits: False (default) = `update_graph` and
    # the lifecycle commits build the post-commit device arrays OFF the
    # fence and flip them under _seq only — no in-flight drain; flushes
    # are epoch-pinned (each seals against the graph arrays of its
    # dispatch index, logs its graph_version) and the fence's three
    # consumers go version-aware (cache graph-version floors, post-flip
    # replica retire, post-flip adapt_tiers). True = the round-17..23
    # drain-ordered fence, bit-identical, kept as the parity twin.
    # Re-provisioning (a shape change) always drains in either mode.
    fenced_commits: bool = False

    def resolved_buckets(self) -> Tuple[int, ...]:
        if self.buckets is None:
            return default_buckets(self.max_batch)
        bs = tuple(sorted(int(b) for b in self.buckets))
        if not bs or bs[0] < 1:
            raise ValueError("buckets must be positive")
        if bs[-1] < self.max_batch:
            raise ValueError(
                f"largest bucket {bs[-1]} < max_batch {self.max_batch}: "
                "a full flush would not fit any bucket"
            )
        return bs


# guards lazy per-slot Event creation (contended only when two waiters
# race to be a slot's FIRST blocking waiter — never on the submit path)
_SLOT_EVENT_LOCK = threading.Lock()


class _Slot:
    """One unique (node_id, params_version) computation; every coalesced
    request for it holds a reference and blocks via :meth:`wait`. ``rid``
    is the slot's journal request id (engine-monotonic; -1 when the
    engine isn't journaling) — the key the lifecycle events thread
    through. ``seq`` is the global arrival stamp `_PendingStripes` orders
    the striped queue by.

    The completion `threading.Event` is LAZY (round 20): submit-path
    throughput is bounded by per-slot construction cost, and most slots
    under `predict`/`submit_many` are polled (``done()``) then read after
    their flush resolves — they never block, so they never pay the Event
    (three allocations + a lock). ``resolved`` is the plain-bool fast
    path (GIL-ordered against `resolve`); the first waiter that actually
    needs to BLOCK installs the event under `_SLOT_EVENT_LOCK` and
    re-checks ``resolved`` after installing, which closes the
    install/resolve race in either interleaving."""

    __slots__ = ("node_id", "version", "_event", "resolved", "value",
                 "error", "enqueue_t", "waiters", "rid", "tenant", "seq")

    def __init__(self, node_id: int, version: int, enqueue_t: float,
                 rid: int = -1, tenant: str = DEFAULT_TENANT):
        self.node_id = node_id
        self.version = version
        self._event: Optional[threading.Event] = None
        self.resolved = False
        self.value: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.enqueue_t = enqueue_t
        # (submit timestamp, tenant) per attached request: latency lands
        # in the global histogram AND the submitting tenant's
        self.waiters: List[Tuple[float, str]] = []
        self.rid = rid
        self.tenant = tenant  # admitting tenant (quota accounting)
        self.seq = -1  # arrival order within the striped pending queue

    def resolve(self, value: Optional[np.ndarray], error=None) -> None:
        self.value = value
        self.error = error
        self.resolved = True
        ev = self._event
        if ev is not None:
            ev.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self.resolved:
            return True
        ev = self._event
        if ev is None:
            with _SLOT_EVENT_LOCK:
                ev = self._event
                if ev is None:
                    ev = self._event = threading.Event()
            if self.resolved:
                # resolve() may have read _event before the install; its
                # write to ``resolved`` precedes this read under the GIL
                return True
        return ev.wait(timeout)


class ServeResult:
    """Handle returned by :meth:`ServeEngine.submit`. May carry a value
    (cache hit), a slot (queued computation), or a per-request error
    (e.g. `ShedError` at admission, an owner failure isolated to this
    request's sub-batch)."""

    __slots__ = ("_slot", "_value", "_error")

    def __init__(self, slot: Optional[_Slot] = None,
                 value: Optional[np.ndarray] = None,
                 error: Optional[BaseException] = None):
        self._slot = slot
        self._value = value
        self._error = error

    def done(self) -> bool:
        return self._slot is None or self._slot.resolved

    def error(self) -> Optional[BaseException]:
        """The request's exception without raising (None if none yet;
        a queued request's error is known only after it resolves)."""
        if self._error is not None:
            return self._error
        if self._slot is not None and self._slot.resolved:
            return self._slot.error
        return None

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Logits row for the requested node (blocks until its flush
        lands; raises the request's exception if it was shed at
        admission or its dispatch failed — per-request: co-flushed
        requests of a healthy sub-batch resolve normally).

        The row is READ-ONLY — it is shared with the embedding cache and
        every coalesced co-waiter. Copy before mutating."""
        if self._error is not None:
            raise self._error
        if self._slot is None:
            return self._value
        if not self._slot.wait(timeout):
            raise TimeoutError("serve request not resolved in time")
        if self._slot.error is not None:
            raise self._slot.error
        return self._slot.value


def _slot_row(slot: "_Slot", timeout: Optional[float]) -> np.ndarray:
    """`ServeResult.result` against a bare slot (the lazy batch path
    skips the handle object entirely) — same wait/raise/return
    sequence, same timeout message."""
    if not slot.wait(timeout):
        raise TimeoutError("serve request not resolved in time")
    if slot.error is not None:
        raise slot.error
    return slot.value


class ResultBatch(collections.abc.Sequence):
    """The handle sequence ``submit_many`` returns (round 22): admission
    keeps the RAW per-request outcome — a `_Slot`, or a ready
    `ServeResult` (cache hit / shed / per-request error) — and builds a
    `ServeResult` only when a caller actually indexes or iterates, so
    per-request handle construction moves off the submit path onto the
    consumer that wants handles. Fully list-compatible for existing
    callers (``len``/index/slice/iterate/truthiness); the batch
    consumers (`ServeEngine.results_many`, ``predict``) read the raw
    entries array-at-a-time and never materialize handles at all.

    The whole-batch vectorized admission path stores one slot per
    UNIQUE key plus the batch's coalesce map (``inv[i]`` = the unique
    index serving request ``i``), so delivery is a per-unique gather
    expanded by ONE fancy-index instead of N per-request reads."""

    __slots__ = ("_items", "_uniq", "_inv")

    def __init__(self, items: Optional[List] = None,
                 uniq: Optional[List] = None,
                 inv: Optional[np.ndarray] = None):
        self._items = items
        self._uniq = uniq
        self._inv = inv

    def __len__(self) -> int:
        if self._items is not None:
            return len(self._items)
        return len(self._inv)

    def _raw(self, i: int):
        if self._items is not None:
            return self._items[i]
        return self._uniq[self._inv[i]]

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        it = self._raw(i)
        return it if isinstance(it, ServeResult) else ServeResult(slot=it)

    def __iter__(self):
        if self._items is not None:
            raws = self._items
        else:
            uniq = self._uniq
            raws = [uniq[j] for j in self._inv.tolist()]
        for it in raws:
            yield it if isinstance(it, ServeResult) else ServeResult(slot=it)

    def __eq__(self, other):
        # list-compatibility: handle wrappers materialize per access, so
        # equality is positional identity of the RAW outcomes (two views
        # of the same admission compare equal; `submit_many([]) == []`
        # stays true)
        if isinstance(other, (list, tuple, ResultBatch)):
            if len(self) != len(other):
                return False
            return all(
                a is b or (isinstance(a, ServeResult)
                           and isinstance(b, ServeResult)
                           and a._slot is not None
                           and a._slot is b._slot)
                for a, b in zip(self, other)
            )
        return NotImplemented

    __hash__ = None  # mutable-sequence convention, like list

    def done(self) -> bool:
        """True when every request's handle would report ``done()`` —
        checked per UNIQUE slot on the vectorized path."""
        raws = self._items if self._items is not None else self._uniq
        for it in raws:
            if isinstance(it, ServeResult):
                if not it.done():
                    return False
            elif not it.resolved:
                return False
        return True

    def gather(self, timeout: Optional[float] = None) -> np.ndarray:
        """All rows as one ``[N, C]`` array in request order — the batch
        twin of ``np.stack([h.result(timeout) for h in handles])``,
        including its error order: the first REQUEST whose handle would
        raise is the one raised here."""
        n = len(self)
        if n == 0:
            return np.zeros((0, 0), np.float32)
        if self._items is None:
            uniq = self._uniq
            errs = None
            for j, slot in enumerate(uniq):
                if not slot.wait(timeout):
                    raise TimeoutError("serve request not resolved in time")
                if slot.error is not None:
                    if errs is None:
                        errs = {}
                    errs[j] = slot.error
            if errs is not None:
                for j in self._inv.tolist():  # request order
                    if j in errs:
                        raise errs[j]
            rows = np.stack([slot.value for slot in uniq])
            return rows[self._inv]
        return np.stack([
            it.result(timeout) if isinstance(it, ServeResult)
            else _slot_row(it, timeout)
            for it in self._items
        ])


@dataclass
class ServeStats:
    """Engine counters. ``requests`` counts every submit; ``coalesced``
    the subset answered by attaching to an existing pending/in-flight slot;
    the cache's own hit/miss/eviction counters live in ``cache``.
    ``dispatches`` is the number of device batches actually launched —
    the acceptance metric "dispatch count < N" reads this.
    ``inflight_peak`` is the largest number of flushes observed between
    assemble and resolve at once (> 1 is direct evidence the window was
    used; bounded by ``max_in_flight + 1`` — a drained flush waiting for
    its window permit, i.e. the one admitting late seeds, is between
    assemble and resolve too). ``spans`` records per-stage
    (assemble/dispatch/resolve) spans on the engine's clock —
    ``spans.overlap_summary()`` is the measured-overlap evidence.

    ``dispatch_calls`` counts dispatch-STAGE entries (including ones that
    errored; ``dispatches`` counts only resolved successes) and
    ``execute_calls`` the device program legs those stages ran — 1 per
    flush on the fused one-program path, 2 on the split path (the round-9
    sample + forward ledger; the split sample leg is itself op-by-op
    eager dispatch, so 2 is that ledger's floor, not an op count). The 2→1
    dispatch claim is OBSERVABLE as ``execute_calls == dispatches`` on a
    fused engine, not inferred. ``late_admitted`` counts seeds admitted
    into an assembled flush's pad lanes (recovered bucket slack)."""

    requests: int = 0
    coalesced: int = 0
    dispatches: int = 0
    dispatched_seeds: int = 0   # unique seeds sent to the device
    padded_seeds: int = 0       # bucket slack rows computed and discarded
    dispatch_calls: int = 0
    execute_calls: int = 0
    late_admitted: int = 0
    tier_promoted: int = 0      # rows moved UP a tier (round 14)
    tier_demoted: int = 0       # rows moved DOWN a tier
    placement_batches: int = 0  # fenced placement applies
    # round-18 flush-ahead prefetch ledger: issued counts disk rows
    # submitted to the read pool ahead of their gather, hit the rows a
    # gather consumed from staging, wasted the rows staged but dropped
    # (fence cancels, failed reads, closure rows the draw never touched)
    tier_prefetch_issued: int = 0
    tier_prefetch_hit: int = 0
    tier_prefetch_wasted: int = 0
    shed: int = 0               # requests refused at admission (round 15)
    request_errors: int = 0     # slots resolved with a per-request error
    undrained: int = 0          # slots abandoned by a bounded stop() drain
    # round-17 streaming-graph counters: graph_deltas counts fenced
    # update_graph commits, delta_edges the edges they appended,
    # delta_tile_writes/spills the pad-lane vs relocation split (the
    # layout-health signal: spills rising means the reserve is being
    # eaten), delta_cache_invalidated the closure-touched cache drops
    graph_deltas: int = 0
    delta_edges: int = 0
    delta_tile_writes: int = 0
    delta_tile_spills: int = 0
    delta_cache_invalidated: int = 0
    # round-21 graph lifecycle: deletions/expiries are masked lane work,
    # reclaims/compactions are the background row economy — together they
    # are the "does the stream actually live forever" signal (expired +
    # reclaimed keeping pace with appended = flat reserve occupancy)
    edges_deleted: int = 0
    edges_expired: int = 0
    tiles_reclaimed: int = 0
    compactions: int = 0
    inflight_peak: int = 0
    dispatch_buckets: Dict[int, int] = field(default_factory=dict)
    cache: HitRateCounter = field(default_factory=HitRateCounter)
    latency: LatencyHistogram = field(default_factory=LatencyHistogram)
    # per-tenant end-to-end latency (round 15): one histogram per tenant
    # that ever submitted — `tenant_latency["t"].percentile(99)` is the
    # per-tenant p99 the admission work is judged by
    tenant_latency: Dict[str, LatencyHistogram] = field(default_factory=dict)
    spans: SpanRecorder = field(default_factory=SpanRecorder)
    # round-24 per-commit serving stall, in MICROSECONDS (the histogram
    # is unit-agnostic; µs keeps flip-only stalls resolvable): fenced
    # mode records the whole drain+fenced-work hold, zero-stall mode the
    # _seq flip hold — the drain-vs-flip evidence `delta_table` prices
    commit_stall: LatencyHistogram = field(
        default_factory=lambda: LatencyHistogram(min_ms=1e-2, max_ms=1e9)
    )

    def tenant_hist(self, tenant: str) -> LatencyHistogram:
        """The tenant's latency histogram, created on first use. Callers
        mutate it under the owning engine's lock; readers snapshot."""
        return tenant_latency_hist(self.tenant_latency, tenant)

    def merge(self, other: "ServeStats") -> "ServeStats":
        """Fold another engine's stats into this one — the cross-shard
        aggregation hook the distributed serve engine uses (one merged view
        over H shard engines: counters add, ``inflight_peak`` is the max
        across shards, histograms/counters/spans merge via their own
        `merge` methods in `quiver_tpu.trace`). Merge into a FRESH
        `ServeStats`, not a live engine's — the source engines keep
        counting into their own objects. Safe against a LIVE source: the
        int fields read atomically under the GIL, the bucket dict is
        snapshotted with the atomic C-level ``.copy()`` (a bare
        ``.items()`` loop would raise RuntimeError if a flush lands a new
        bucket mid-iteration), and the histogram/counter/span merges take
        their own locks — the result is a consistent-enough snapshot, not
        a fence. Returns self for chaining."""
        self.requests += other.requests
        self.coalesced += other.coalesced
        self.dispatches += other.dispatches
        self.dispatched_seeds += other.dispatched_seeds
        self.padded_seeds += other.padded_seeds
        self.dispatch_calls += other.dispatch_calls
        self.execute_calls += other.execute_calls
        self.late_admitted += other.late_admitted
        self.tier_promoted += other.tier_promoted
        self.tier_demoted += other.tier_demoted
        self.placement_batches += other.placement_batches
        self.tier_prefetch_issued += other.tier_prefetch_issued
        self.tier_prefetch_hit += other.tier_prefetch_hit
        self.tier_prefetch_wasted += other.tier_prefetch_wasted
        self.shed += other.shed
        self.request_errors += other.request_errors
        self.undrained += other.undrained
        self.graph_deltas += other.graph_deltas
        self.delta_edges += other.delta_edges
        self.delta_tile_writes += other.delta_tile_writes
        self.delta_tile_spills += other.delta_tile_spills
        self.delta_cache_invalidated += other.delta_cache_invalidated
        self.edges_deleted += other.edges_deleted
        self.edges_expired += other.edges_expired
        self.tiles_reclaimed += other.tiles_reclaimed
        self.compactions += other.compactions
        self.inflight_peak = max(self.inflight_peak, other.inflight_peak)
        for b, n in other.dispatch_buckets.copy().items():
            self.dispatch_buckets[b] = self.dispatch_buckets.get(b, 0) + n
        for t, h in other.tenant_latency.copy().items():
            self.tenant_hist(t).merge(h)
        self.cache.merge(other.cache)
        self.latency.merge(other.latency)
        self.spans.merge(other.spans)
        self.commit_stall.merge(other.commit_stall)
        return self

    def snapshot(self) -> Dict[str, object]:
        return {
            "requests": self.requests,
            "coalesced": self.coalesced,
            "dispatches": self.dispatches,
            "dispatched_seeds": self.dispatched_seeds,
            "padded_seeds": self.padded_seeds,
            "dispatch_calls": self.dispatch_calls,
            "execute_calls": self.execute_calls,
            "late_admitted": self.late_admitted,
            "tier_promoted": self.tier_promoted,
            "tier_demoted": self.tier_demoted,
            "placement_batches": self.placement_batches,
            "tier_prefetch_issued": self.tier_prefetch_issued,
            "tier_prefetch_hit": self.tier_prefetch_hit,
            "tier_prefetch_wasted": self.tier_prefetch_wasted,
            "shed": self.shed,
            "request_errors": self.request_errors,
            "undrained": self.undrained,
            "graph_deltas": self.graph_deltas,
            "delta_edges": self.delta_edges,
            "delta_tile_writes": self.delta_tile_writes,
            "delta_tile_spills": self.delta_tile_spills,
            "delta_cache_invalidated": self.delta_cache_invalidated,
            "edges_deleted": self.edges_deleted,
            "edges_expired": self.edges_expired,
            "tiles_reclaimed": self.tiles_reclaimed,
            "compactions": self.compactions,
            "inflight_peak": self.inflight_peak,
            "dispatch_buckets": dict(self.dispatch_buckets),
            "cache": self.cache.snapshot(),
            "latency": self.latency.snapshot(),
            "tenant_latency": {
                t: self.tenant_latency[t].snapshot()
                for t in sorted(self.tenant_latency)
            },
            "overlap": self.spans.overlap_summary(),
            "commit_stall_us": self.commit_stall.snapshot(),
        }


class _Flush:
    """Per-flush state between assemble and resolve: the drained slots and
    the params snapshot the dispatch will run under. Dispatch ORDER is not
    carried here — it is the log-append/key-draw order the sequencing lock
    imposes (`ServeEngine._dispatch_index` counts it). ``bucket`` is fixed
    at drain time; late admission may append to ``keys``/``slots`` up to it
    until `_seal_assembled` closes the flush. The fused path carries the
    drawn sampler ``key`` + the ``padded`` seed batch into its one-program
    dispatch; the split path carries the pre-run sample ``ds``.

    Round 20 (array-native internals): once sealed, the flush also carries
    SLOT ARRAYS — ``ids`` (int64 seed ids), ``rids`` (int64 journal
    request ids, -1 when the journal is off) and ``tenant_ix`` (int32
    indices into the engine's interned tenant table, built on first
    sight) — aligned with ``slots`` so downstream consumers (result
    delivery, replay tooling, the frontend bench) address the batch by
    slot INDEX instead of walking per-request objects. ``slots`` itself
    stays: waiters/version/resolution state is per-request by nature."""

    __slots__ = ("keys", "slots", "params", "seeds", "bucket", "ds", "key",
                 "padded", "extra", "error", "fid", "ids", "rids",
                 "tenant_ix", "graph_version", "binding")

    def __init__(self, keys, slots, params):
        self.keys = keys
        self.slots = slots
        self.params = params
        self.seeds = None
        self.bucket = 0
        self.ds = None
        self.key = None
        self.padded = None
        # round-24 epoch pin, stamped at seal (under _seq): the graph
        # version this flush dispatches against, plus the fused program's
        # persistent-argument snapshot (table, map, graph) of that epoch —
        # a zero-stall commit rebinding mid-flight cannot retarget it
        self.graph_version = 0
        self.binding = None
        self.ids = None        # int64 [n] seed ids (sealed)
        self.rids = None       # int64 [n] journal rids (sealed)
        self.tenant_ix = None  # int32 [n] interned tenant indices (sealed)
        # extra padded per-seed dispatch arguments (round 19: the temporal
        # workload's query-time vector); None on the plain engine
        self.extra = None
        self.error: Optional[BaseException] = None
        # journal flush id == the dispatch index `_seal_assembled` will
        # draw (assemble and seal happen under one _seq hold, so nothing
        # can interleave an increment between them)
        self.fid = -1


def _admit_chunk_fast(eng, keys, nodes, tenants, i, now, events,
                      results) -> Tuple[int, bool]:
    """Vectorized chunk admission (round 20 tentpole) — the fast body
    behind `ServeEngine._submit_keyed_many` and its router twin. The
    caller holds ALL stripe locks and has checked the per-request slow
    triggers are off (no workload tap, no queue-depth shedding); this
    body then admits requests ``[i, n)`` with ONE engine-lock hold, ONE
    batched cache probe per block (`EmbeddingCache.get_many`), C-level
    dict ops for the coalesce probe/insert, and bulk stats/rid updates.
    The per-request DECISION sequence (cache hit -> coalesce -> fresh
    insert, in request order) is identical to `_admit_one_locked`; only
    the mechanics are amortized, so dispatch logs, journal streams, rid
    values and counters stay bit-identical to the scalar path.

    Cache probes run ahead of admission in blocks no larger than the
    guaranteed-consumable room ``max_batch - len(pending)``: a fill
    needs that many fresh inserts, so it can only land on a block's
    LAST entry — probe side effects (LRU touches, hit/miss counters)
    never outrun the requests actually admitted before an inline flush.

    Returns ``(i, need_flush)``. Stops early (``need_flush`` False,
    ``i < n``) when a late-admission window is open — the caller's
    per-request loop handles pad-slack admission; a window cannot OPEN
    mid-chunk because publishing one needs the stripe locks the caller
    holds, and it cannot CLOSE because sealing takes the engine lock
    held here."""
    n = len(keys)
    pend = eng._pending
    maps = pend.maps
    tmaps = pend.tenants
    ns = pend.n
    skey = pend.stripe_key
    arrival = pend._arrival
    infl_get = eng._inflight.get
    cache_many = eng.cache.get_many
    stats = eng.stats
    clock = eng._clock
    max_batch = eng.config.max_batch
    plen = len(pend)
    requests = 0
    coalesced = 0
    ev_append = events.append
    with eng._lock:
        if eng._open is not None:
            return i, False
        ver = eng.params_version
        jr_on = eng.journal.enabled
        rid = eng._next_rid
        while i < n:
            room = max_batch - plen
            if room < 1:
                room = 1
            j = i + room
            if j > n:
                j = n
            for v in cache_many(keys[i:j], ver):
                k = keys[i]
                node = nodes[i]
                ten = tenants[i]
                requests += 1
                if v is not None:  # cache hit: served on the spot
                    ms = (clock() - now) * 1e3
                    stats.latency.record_ms(ms)
                    stats.tenant_hist(ten).record_ms(ms)
                    if jr_on:
                        ev_append(("cache_hit", -1, -1, node, 0))
                    results[i] = ServeResult(value=v)
                    i += 1
                    continue
                s = skey(k) % ns
                slot = maps[s].get(k) or infl_get(k)
                if slot is not None and slot.version == ver:
                    coalesced += 1
                    if jr_on:
                        ev_append(("coalesce", slot.rid, -1, node, 0))
                else:
                    r = -1
                    if jr_on:
                        r = rid
                        rid += 1
                    slot = _Slot(k, ver, now, rid=r, tenant=ten)
                    slot.seq = next(arrival)
                    maps[s][k] = slot
                    t = tmaps[s]
                    t[ten] = t.get(ten, 0) + 1
                    if jr_on:
                        ev_append(("submit", r, -1, node, 0))
                    plen += 1
                slot.waiters.append((now, ten))
                results[i] = slot  # handle built lazily by ResultBatch
                i += 1
                if plen >= max_batch:
                    eng._next_rid = rid
                    stats.requests += requests
                    stats.coalesced += coalesced
                    return i, True
        eng._next_rid = rid
    stats.requests += requests
    stats.coalesced += coalesced
    return i, False


def _batch_uniq(arr: np.ndarray):
    """First-occurrence unique decomposition of a submit batch:
    ``(uniq_ix, inv, counts)`` where ``uniq_ix`` indexes the batch's
    unique keys in ARRIVAL (first-occurrence) order, ``inv[i]`` is the
    unique index serving request ``i``, and ``counts`` the per-unique
    request multiplicity. Works on int id arrays and on structured
    (node, t) arrays alike. Returns None when the array holds NaNs —
    ``np.unique`` collapses equal NaNs while dict keys built from
    distinct float objects do not, so those batches take the
    per-request path."""
    if arr.dtype.kind == "f" and np.isnan(arr).any():
        return None
    if arr.dtype.names is not None:
        for name in arr.dtype.names:
            f = arr[name]
            if f.dtype.kind == "f" and np.isnan(f).any():
                return None
    _, first, inv, counts = np.unique(
        arr, return_index=True, return_inverse=True, return_counts=True
    )
    order = np.argsort(first)  # sorted-unique -> arrival order
    rank = np.empty(order.shape[0], np.int64)
    rank[order] = np.arange(order.shape[0])
    return first[order], rank[inv], counts[order]


def _admit_batch_vector(eng, keys, tenant: str, now: float, uniq_ix,
                        inv, counts) -> Optional[ResultBatch]:
    """WHOLE-batch vectorized admission (round 22) — the per-UNIQUE-key
    admission body behind `submit_many` when nothing per-request can
    happen: the journal is off (no rid draws, no per-request events),
    the cache is empty-by-config (no hit can short-circuit), one tenant
    covers the batch, and the whole batch fits the pending queue without
    an inline fill-flush. Under those gates the scalar decision sequence
    collapses to "coalesce or insert, per unique key": duplicates inside
    the batch attach to the first occurrence's slot exactly as the
    per-request loop would attach them, so slots, arrival stamps,
    waiter lists and counters are bit-identical to N scalar submits —
    while the per-REQUEST work drops to one np.unique.

    Caller holds ALL stripe locks and has checked the engine-shape
    gates; this checks the state gates (open window, room) under
    ``_lock`` and returns None to fall back. Shared by the single-host
    engine and the router (stripe mapping via ``pend.stripe_of`` keeps
    it owner-partition-correct there)."""
    pend = eng._pending
    maps = pend.maps
    tmaps = pend.tenants
    stripe_of = pend.stripe_of
    infl_get = eng._inflight.get
    n_uniq = uniq_ix.shape[0]
    with eng._lock:
        if eng._open is not None:
            return None
        if len(pend) + n_uniq >= eng.config.max_batch:
            # an inline fill-flush could land mid-batch; the per-request
            # path owns that interleaving
            return None
        ver = eng.params_version
        arrival = pend._arrival
        w = (now, tenant)
        uniq_slots = [None] * n_uniq
        new = 0
        ux = uniq_ix.tolist()
        cts = counts.tolist()
        for j in range(n_uniq):
            k = keys[ux[j]]
            s = stripe_of(k)
            slot = maps[s].get(k) or infl_get(k)
            if slot is None or slot.version != ver:
                slot = _Slot(k, ver, now, rid=-1, tenant=tenant)
                slot.seq = next(arrival)
                maps[s][k] = slot
                t = tmaps[s]
                t[tenant] = t.get(tenant, 0) + 1
                new += 1
            c = cts[j]
            if c == 1:
                slot.waiters.append(w)
            else:
                slot.waiters.extend([w] * c)
            uniq_slots[j] = slot
    n = len(keys)
    stats = eng.stats
    stats.requests += n
    stats.coalesced += n - new
    # the scalar path probes the (empty, untapped) cache per request and
    # counts a miss each time — same evidence, one bulk move
    eng.cache.counters.miss(n)
    return ResultBatch(uniq=uniq_slots, inv=inv)


_REPEAT_NONE = itertools.repeat(None)
_WAITER_T0 = operator.itemgetter(0)
_WAITER_TENANT = operator.itemgetter(1)


def _pop_inflight_many(eng, keys) -> None:
    """C-level batched ``_inflight.pop(k, None)`` over a flush's keys
    (the deque(maxlen=0) idiom consumes the map object without a
    Python-level loop)."""
    if eng._inflight:
        collections.deque(
            map(eng._inflight.pop, keys, _REPEAT_NONE), maxlen=0
        )


def _record_waiter_latency(eng, slots, now: float) -> None:
    """The per-waiter latency recording of `_resolve`, vectorized: one
    flatten of the flush's waiter lists, one ``(now - t0) * 1e3`` vector
    (element-for-element the scalar expression), one bulk histogram
    fold for the global histogram and one per tenant. Bucket counts are
    bit-identical to the scalar loop (`LatencyHistogram.record_ms_many`);
    only ``sum_ms`` accumulates in vector order."""
    ws = list(itertools.chain.from_iterable([s.waiters for s in slots]))
    if not ws:
        return
    t0s = np.fromiter(map(_WAITER_T0, ws), np.float64, len(ws))
    ms = (now - t0s) * 1e3
    eng.stats.latency.record_ms_many(ms)
    tenants = set(map(_WAITER_TENANT, ws))
    if len(tenants) == 1:
        eng.stats.tenant_hist(tenants.pop()).record_ms_many(ms)
    else:
        by: Dict[str, List[int]] = {}
        for ix, wt in enumerate(ws):
            by.setdefault(wt[1], []).append(ix)
        for ten, ixs in by.items():
            eng.stats.tenant_hist(ten).record_ms_many(ms[ixs])


def _resolve_block(eng, fl, logits: np.ndarray, now: float) -> None:
    """Stage-3 fast path (round 22 tentpole), caller holds ``_lock`` and
    has checked the guards: no flush/slot errors, no slot already
    resolved (abandonment by a bounded stop() resolves a flush's slots
    all-or-nothing, so ``slots[0]`` answers for the flush), versions
    uniform at the live ``params_version`` (the update_params fence).
    The scalar loop then collapses to: one batched inflight pop, ONE
    contiguous logits slice handed out as per-slot row views (the same
    row object goes to the slot AND the cache, as in the scalar path),
    one `EmbeddingCache.put_many`, one per-slot publication pass with
    the lazy-Event wake, and one vectorized waiter-latency fold. Shared
    by `ServeEngine._resolve` and `DistServeEngine._resolve`."""
    slots = fl.slots
    n = len(slots)
    _pop_inflight_many(eng, fl.keys)
    rows = list(logits[:n])  # n row views, made at C speed
    if eng.cache.capacity != 0:
        eng.cache.put_many(fl.keys, eng.params_version, rows,
                           gv=fl.graph_version)
    for slot, row in zip(slots, rows):
        slot.value = row
        slot.resolved = True
        ev = slot._event
        if ev is not None:
            ev.set()
    _record_waiter_latency(eng, slots, now)


class _CommitCounterSource:
    """`counter_samples()` adapter over an engine's per-commit sample
    ring — `trace.chrome_trace_events` renders any source bearing
    ``counter_samples()`` as ``ph:"C"`` counter tracks, so the
    graph-version staircase and the per-commit stall ride the trace's
    counter lane (observe-only; round 24)."""

    def __init__(self, samples):
        self._samples = samples

    def counter_samples(self):
        return list(self._samples)


class ServeEngine:
    """See the module docstring for the design; docs/api.md for the
    contract. Typical use::

        engine = ServeEngine(model, params, sampler, feature,
                             ServeConfig(max_batch=32, max_delay_ms=2.0))
        engine.warmup()                   # pre-trace every bucket shape
        with engine:                      # starts the background flushers
            logits = engine.predict([node_id])[0]

    or fully synchronous (no thread)::

        h = engine.submit(node_id)
        engine.flush()
        logits = h.result()
    """

    # subclasses that understand the temporal dispatch shape (the extra
    # query-time argument, composite (node, t) keys) set this — see
    # quiver_tpu.workloads.serving.TemporalServeEngine
    _temporal_capable = False

    def __init__(self, model, params, sampler, feature,
                 config: Optional[ServeConfig] = None):
        self.config = config or ServeConfig()
        if (getattr(sampler, "temporal", None) is not None
                and not self._temporal_capable):
            raise TypeError(
                "temporal-bound samplers need the temporal engine — use "
                "quiver_tpu.workloads.TemporalServeEngine (this engine "
                "would dispatch without a query time)"
            )
        if self.config.max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        if self.config.dispatch_mode not in ("auto", "fused", "split"):
            raise ValueError(
                f"unknown dispatch_mode {self.config.dispatch_mode!r}"
            )
        if self.config.tier_prefetch_at not in ("submit", "assemble"):
            raise ValueError(
                f"unknown tier_prefetch_at {self.config.tier_prefetch_at!r}"
            )
        self._buckets = self.config.resolved_buckets()
        self._apply = _cached_apply(model)
        self._params = params
        self._sampler = sampler
        self._feature = feature
        # fused one-dispatch path: one pre-bindable program per bucket when
        # the sampler/feature pair supports it (see ServeConfig.dispatch_mode)
        self._programs: Optional[BucketPrograms] = None
        if self.config.dispatch_mode != "split":
            try:
                self._programs = BucketPrograms(model, sampler, feature)
            except (TypeError, AttributeError) as exc:
                if self.config.dispatch_mode == "fused":
                    raise ValueError(
                        f"dispatch_mode='fused' but the serve step cannot "
                        f"fuse: {exc}"
                    ) from exc
        self._clock = self.config.clock
        self.stats = ServeStats()
        # request-lifecycle journal (ServeConfig.journal_events; the
        # shared NULL_JOURNAL's emit is one attribute check when off)
        self.journal = (
            EventJournal(self.config.journal_events, clock=self._clock)
            if self.config.journal_events > 0
            else NULL_JOURNAL
        )
        self._next_rid = 0  # journal request ids (guarded by _lock)
        # round-13 workload telemetry (ServeConfig.workload; observe-only)
        self.workload = (
            WorkloadMonitor(self.config.workload, clock=self._clock)
            if self.config.workload is not None
            else None
        )
        self.cache = EmbeddingCache(self.config.cache_entries,
                                    counters=self.stats.cache)
        if self.workload is not None:
            self.cache.workload = self.workload
        if hasattr(feature, "tier_counter"):
            # tiered features attribute gathered rows per tier into the
            # monitor (Feature/QuantizedFeature; raw tables and in-jit
            # fused gathers are single-tier by construction). The LAST
            # engine built over a feature owns its tap: a workload-less
            # engine explicitly DETACHES any stale counter a previous
            # engine left behind, so a reused feature never pays the
            # attribution scan for (or counts into) a dead monitor.
            feature.tier_counter = (
                self.workload.gathers if self.workload is not None else None
            )
        if hasattr(feature, "row_tap"):
            # round-14 row-access sketch tap (WorkloadConfig.row_topk):
            # same last-engine-owns-the-tap rule as tier_counter
            feature.row_tap = (
                self.workload.observe_rows
                if self.workload is not None
                and self.workload.row_sketch is not None
                else None
            )
        # round-14 adaptive tiers: the feature owning a TierStore under
        # the serve wrappers, or None (static placement — nothing to
        # adapt). placement_version counts fenced placement batches, the
        # exact analog of params_version for tier moves.
        from ..tiers import find_tiered_feature

        self._tier_feature = find_tiered_feature(feature)
        self.placement_version = 0
        self.tier_adapt_errors = 0  # failed background adapt passes
        self.compact_errors = 0     # failed background compaction passes
        self.retention_errors = 0   # failed wall-clock TTL passes (r23)
        self.retention_passes = 0   # completed wall-clock TTL passes
        # round-18 flush-ahead prefetch: bind the tier store's staging
        # buffer when the config asks for it AND the feature can serve it
        # (adaptive store + read pool); inert otherwise — a prefetch-on
        # config over a DRAM-resident feature costs nothing
        self._prefetch_store = None
        # seeds the last submit-time walk covered (tier_prefetch_at=
        # "submit"): the assemble-time catch-all only walks what the
        # submit batch missed. Safe across flushes — staged rows outlive
        # their issuer until consumed, and every fence clears both.
        self._pf_walked: frozenset = frozenset()
        if self.config.tier_prefetch and self._tier_feature is not None:
            store = self._tier_feature.tier_store
            if store.read_pool is not None:
                store.enable_prefetch(
                    max_rows=self.config.tier_prefetch_max_rows,
                    listener=self._on_prefetch_event,
                )
                self._prefetch_store = store
        self.params_version = 0
        # round-17 streaming graphs: graph_version counts fenced delta
        # commits (the analog of params_version for topology);
        # pending_delta accumulates staged edge arrivals (stage_edges)
        # until update_graph commits them — both guarded by _lock
        self.graph_version = 0
        self.pending_delta = None
        # round-21 lifecycle: the deterministic retention clock (None when
        # retention is off) — a pure function of committed timestamps, so
        # two replicas fed the same commit stream expire identical lanes
        if self.config.stream_retention_window > 0:
            from ..lifecycle import RetentionPolicy

            self.retention = RetentionPolicy(
                self.config.stream_retention_window
            )
        else:
            self.retention = None
        self.dispatch_log: List[Tuple[np.ndarray, int]] = []
        # round-24 epoch stamps, index-aligned with dispatch_log: entry i
        # is the graph_version flush i sealed (and dispatched) against —
        # the replay tooling's per-epoch filter. A parallel list, not a
        # tuple-shape change: the log entry tuples are pinned by tests
        # and the round-21 CI smoke.
        self.dispatch_graph_versions: List[int] = []
        # queue state (round 20): _pending is the STRIPED pending store —
        # per-stripe dicts of slots not yet flushed (merged arrival order
        # = the rounds-8–19 FIFO, bit for bit), per-stripe locks so
        # concurrent submitters don't serialize; _inflight (guarded by
        # _lock) holds slots snapshot-ed by a running flush. Per-tenant
        # pending counts live inside the store (insert/pop maintain them)
        self._pending = _PendingStripes(self.config.submit_stripes)
        self._inflight: Dict[int, _Slot] = {}
        import collections

        # round-15 deterministic shed decisions log [(request_seq,
        # tenant, node_id)] — a bounded ring: sustained overload (when it
        # fills) must not leak
        self.shed_log = collections.deque(maxlen=65536)
        # round-24 per-commit counter samples (name, t, value) for the
        # Chrome-trace counter lane: graph_version + commit_stall_us at
        # every commit flip. Bounded ring; observe-only.
        self._commit_samples = collections.deque(maxlen=4096)
        # round-20 array-native flush internals: per-engine tenant-name
        # interning for the flush-level tenant-index arrays (grown on
        # demand at seal; order = first-seen)
        self._tenant_ids: Dict[str, int] = {}
        # the assembled-but-not-yet-sealed flush accepting late admissions
        # (guarded by _lock; non-None only while its flusher holds _seq)
        self._open: Optional[_Flush] = None
        self._lock = threading.Lock()          # queue + cache-version state
        # fence condition over _lock: update_params waits here for every
        # in-flight flush to resolve before swapping the weights
        self._fence = threading.Condition(self._lock)
        # sequencing lock: orders queue drain + dispatch-index assignment +
        # dispatch-log append + the sampler's key draw, so the key stream
        # and the replay log stay deterministic in dispatch order
        self._seq = threading.Lock()
        # bounded in-flight window: at most max_in_flight flushes between
        # assemble and resolve (blocking acquire = backpressure on callers)
        self._window = threading.BoundedSemaphore(self.config.max_in_flight)
        self._inflight_flushes = 0             # guarded by _lock
        self._dispatch_index = 0               # guarded by _seq
        # round-24 commit serialization: one zero-stall commit at a time
        # (update_graph / expire_edges / compact_graph / the lifecycle
        # daemons) — the off-fence build phase must not interleave with
        # another commit's. RLock: a commit's retention pass may re-enter.
        # Traffic never takes it; it orders only commit vs commit.
        self._commit_lock = threading.RLock()
        # parity escape hatch: True forces the pre-round-22 per-slot
        # resolve loop — the reference the bit-parity tests (and
        # bench_frontend's in-run parity legs) compare the block
        # resolution against. Never set on a serving path.
        self._scalar_resolve = False
        self._seed_bufs: Dict[Tuple[int, object], np.ndarray] = {}
        self._threads: List[threading.Thread] = []
        self._running = False

    # -- request path -----------------------------------------------------

    def submit(self, node_id: int,
               tenant: Optional[str] = None) -> ServeResult:
        """Enqueue one node-prediction request; returns a handle. Fills of
        ``max_batch`` flush inline on the submitting thread. A seed
        arriving while a flush sits assembled-but-not-yet-dispatched (late
        admission enabled, pad slack left) rides that flush's pad lanes
        instead of waiting a whole extra flush.

        Round 20: this is `submit_many` of ONE — the scalar spelling
        stays the public API, but the cache-check/coalesce/shed/admit/
        flush-at-fill sequence lives once in `_admit_one_locked`, so
        scalar and batch admission are bit-identical by construction
        (pinned in tests/test_frontend.py).

        ``tenant`` names the submitting tenant (round 15): its latency
        lands in ``stats.tenant_latency[tenant]``, its queue share is
        bounded by ``tenant_weights``/``max_queue_depth`` (an over-quota
        submit at a full queue returns a `ShedError`-carrying result —
        deterministic, logged in ``shed_log``), and flush quotas drain
        tenants in weighted proportion. Cache hits and coalesces never
        shed. KEEP IN LOCKSTEP with `DistServeEngine.submit`
        (serve/dist.py): the distributed router's hosts=1 bit-parity
        contract rides this exact admission sequence."""
        return self.submit_many((node_id,), tenant=tenant)[0]

    def submit_many(self, node_ids, t=None,
                    tenant: Union[None, str, Sequence[str]] = None,
                    ) -> ResultBatch:
        """Vectorized batch submit (round 20): admit N requests array-at-
        a-time — one stripe-lock acquisition per admission chunk, one
        clock read, one batched journal append (`EventJournal.
        record_many`), a list-compatible `ResultBatch` of handles back
        in request order (round 22: handle objects materialize lazily;
        `results_many` consumes the batch without them, and on the
        production-shaped config — journal off, cache 0, no shedding —
        the whole batch admits per UNIQUE key in one np.unique). The
        admission DECISIONS (cache probe order, coalescing, shedding,
        late admission, flush-at-fill) are made per request in request
        order — by the vectorized `_admit_chunk_fast` body in the
        common case (no shedding, no workload tap, no open
        late-admission window), by the same `_admit_one_locked` body
        the scalar path runs otherwise; the two are decision-for-
        decision identical, so dispatch logs are bit-identical to N
        scalar ``submit`` calls — the batch path amortizes the host
        mechanics, never the semantics. Fills of ``max_batch`` flush
        INLINE mid-batch, exactly where the scalar sequence would
        flush.

        ``t`` is rejected here (temporal engines override with vectorized
        query-time quantization); ``tenant`` is None, one tenant name for
        the whole batch, or a per-request sequence aligned with
        ``node_ids``."""
        if t is not None:
            raise TypeError(
                "t= is a temporal-serving argument (TemporalServeEngine / "
                "TemporalDistServeEngine); this engine serves untimed nodes"
            )
        ids = np.asarray(node_ids, dtype=np.int64).reshape(-1)
        keys = ids.tolist()  # python ints: dict keys + journal payloads
        return self._submit_keyed_many(keys, keys, tenant, uniq_arr=ids)

    def _vector_admissible(self, tenant) -> bool:
        """Engine-shape gates for the whole-batch vectorized admission
        (`_admit_batch_vector`): nothing configured that makes admission
        inherently per-request — no workload tap, no shedding, no
        journal (rid draws + per-request events), no cache that could
        hit, one tenant name. State gates (open late-admission window,
        queue room) are checked under the locks."""
        return (self.workload is None
                and self.config.max_queue_depth == 0
                and not self.journal.enabled
                and self.cache.capacity == 0
                and self.cache.workload is None
                and (tenant is None or isinstance(tenant, str)))

    def _submit_keyed_many(self, keys: List, nodes: List[int],
                           tenant, uniq_arr: Optional[np.ndarray] = None,
                           ) -> ResultBatch:
        """The batch admission loop behind `submit_many` (and, at N=1,
        `submit`/`_submit_keyed`): chunked single-lock holds over the
        striped pending store, per-request decisions in request order,
        one journal append per chunk, inline flush at every fill — the
        scalar admission sequence, amortized. KEEP IN LOCKSTEP with
        `DistServeEngine._submit_keyed_many`.

        When the caller supplies ``uniq_arr`` (the batch's keys as one
        np array) and the `_vector_admissible` gates pass, the whole
        batch is admitted per UNIQUE key by `_admit_batch_vector` —
        one np.unique, no per-request Python work — falling back here
        whenever a per-request decision could arise."""
        n = len(keys)
        if n and uniq_arr is not None and self._vector_admissible(tenant):
            pre = _batch_uniq(uniq_arr)
            if pre is not None:
                ten = DEFAULT_TENANT if tenant is None else str(tenant)
                now = self._clock()
                with self._pending.all_locks():
                    rb = _admit_batch_vector(self, keys, ten, now, *pre)
                if rb is not None:
                    return rb
        tenants = resolve_tenants(tenant, n)
        results: List[Optional[ServeResult]] = [None] * n
        max_batch = self.config.max_batch
        jr = self.journal
        i = 0
        while i < n:
            events: List[Tuple] = []
            need_flush = False
            now = self._clock()
            with self._pending.all_locks():
                if (self.workload is None
                        and self.config.max_queue_depth == 0):
                    # the round-20 tentpole: vectorized chunk admission
                    # (one _lock hold, blocked cache probes, bulk
                    # stats). Falls through to the per-request body
                    # when a decision needs it (an open late-admission
                    # window) or when shedding / the workload tap are
                    # configured (checked above — those are inherently
                    # per-request).
                    i, need_flush = _admit_chunk_fast(
                        self, keys, nodes, tenants, i, now, events,
                        results,
                    )
                while i < n and not need_flush:
                    res = self._admit_one_locked(
                        keys[i], nodes[i], tenants[i], now, events
                    )
                    results[i] = res
                    i += 1
                    if (res._slot is not None
                            and len(self._pending) >= max_batch):
                        need_flush = True
            jr.record_many(events)
            if need_flush:
                # flush-ahead prefetch at SUBMIT time (round 18): issue
                # the filled bucket's closure reads on THIS thread before
                # the flush work starts — when another flush already
                # holds the dispatch path, the reads overlap its whole
                # service time. Observe-only: never reorders admission,
                # never fails a submit (the assemble-time pass is the
                # catch-all).
                if (self._prefetch_store is not None
                        and self.config.tier_prefetch_at == "submit"):
                    self._prefetch_pending()
                self.flush()
        return ResultBatch(items=results)

    def _submit_keyed(self, key, node: int,
                      tenant: Optional[str]) -> ServeResult:
        """Single-key admission under ONE stripe lock (the concurrent-
        scalar-submit fast path: threads submitting keys in different
        stripes never share a lock). Same `_admit_one_locked` body as the
        batch path. ``key`` is the coalescing/cache identity (the plain
        node id on this engine; ``(node, t_bucket)`` on the round-19
        temporal engine; a pair-endpoint composite via `_PairServing`)
        and ``node`` the seed id telemetry/journal/shed entries carry."""
        tenant = DEFAULT_TENANT if tenant is None else str(tenant)
        now = self._clock()
        events: List[Tuple] = []
        with self._pending.lock_for(key):
            res = self._admit_one_locked(key, node, tenant, now, events)
            need_flush = (res._slot is not None
                          and len(self._pending) >= self.config.max_batch)
        self.journal.record_many(events)
        if need_flush:
            if (self._prefetch_store is not None
                    and self.config.tier_prefetch_at == "submit"):
                self._prefetch_pending()
            self.flush()
        return res

    def _admit_one_locked(self, key, node: int, tenant: str, now: float,
                          events: List[Tuple]) -> ServeResult:
        """The ONE cache-check/coalesce/shed/admit sequence behind every
        submit spelling, scalar or batch (round 20: extracted so the two
        can never drift). Caller holds ``key``'s stripe lock (or all
        stripe locks on the batch path); ``_lock`` is taken here only for
        the rid draw + late-admission window (stripe-before-_lock, per
        the `_PendingStripes` hierarchy). Journal events append to
        ``events`` as ``(kind, rid, fid, a, b)`` for the caller's batched
        `record_many`. One body, so a future change to shedding or
        admission can never silently skip a workload."""
        self.stats.requests += 1
        wl = self.workload
        if wl is not None:
            wl.observe_seed(node)  # observe-only frequency tap
        cached = self.cache.get(key, self.params_version)
        if cached is not None:
            ms = (self._clock() - now) * 1e3
            self.stats.latency.record_ms(ms)
            self.stats.tenant_hist(tenant).record_ms(ms)
            events.append(("cache_hit", -1, -1, node, 0))
            return ServeResult(value=cached)
        slot = self._pending.get(key) or self._inflight.get(key)
        if slot is not None and slot.version == self.params_version:
            self.stats.coalesced += 1
            events.append(("coalesce", slot.rid, -1, node, 0))
        else:
            if self._shed_locked(tenant):
                self.stats.shed += 1
                self.shed_log.append((self.stats.requests, tenant, node))
                events.append(("shed", -1, -1, node, 0))
                return ServeResult(error=ShedError(
                    f"queue depth {len(self._pending)} >= "
                    f"{self.config.max_queue_depth} and tenant "
                    f"{tenant!r} is at its weighted quota"
                ))
            admitted_late = False
            with self._lock:
                rid = -1
                if self.journal.enabled:
                    rid = self._next_rid
                    self._next_rid += 1
                slot = _Slot(key, self.params_version, now, rid=rid,
                             tenant=tenant)
                fl = self._open
                if fl is not None and len(fl.keys) < fl.bucket:
                    # late admission into the open flush's pad slack (its
                    # update_params fence guarantees the versions agree:
                    # _open only exists while its flusher holds _seq)
                    fl.keys.append(key)
                    fl.slots.append(slot)
                    self._inflight[key] = slot
                    self.stats.late_admitted += 1
                    events.append(("late_admit", rid, fl.fid, node, 0))
                    admitted_late = True
            if not admitted_late:
                # still under the stripe lock: the probe-above/insert-
                # here pair is atomic per key, and no drain can land in
                # between (assemble needs every stripe lock)
                self._pending.insert_unlocked(key, slot, tenant)
                events.append(("submit", rid, -1, node, 0))
        slot.waiters.append((now, tenant))
        return ServeResult(slot=slot)

    def _prefetch_pending(self) -> None:
        """Walk+issue the current pending keys' expected closure and
        remember them so the assemble-time pass skips the repeat walk
        (`PrefetchBuffer` dedups the READS either way; this skips the
        redundant closure BFS on the serve path)."""
        keys = self._pending.ordered_keys()
        if not keys:
            return
        try:
            self.prefetch_seeds(np.asarray(keys, np.int64))
            # REPLACE the memo (never union): it must mean "walked and
            # certainly still staged" — keys from older batches may have
            # been consumed already, and skipping their re-walk would
            # quietly zero their hit rate on a later arrival
            self._pf_walked = frozenset(keys)
        except Exception:
            pass

    def _shed_locked(self, tenant: str) -> bool:
        return shed_decision(
            len(self._pending), self._pending.tenant_count(tenant), tenant,
            self.config.max_queue_depth, self.config.tenant_weights,
        )

    def predict(self, node_ids, timeout: Optional[float] = None,
                tenants: Optional[Sequence[str]] = None) -> np.ndarray:
        """Blocking convenience: submit every id, make sure they flush
        (inline when no background thread is running), return ``[len(ids),
        C]`` logits in request order. ``tenants`` (aligned with
        ``node_ids``) stamps each submission's tenant — the round-16
        owner-side QoS hook: a router forwarding a sub-batch passes the
        submitting tenants through, so this engine's
        ``tenant_weights`` flush quotas hold END-TO-END, not just at
        router admission."""
        ids = np.asarray(node_ids).reshape(-1)
        if tenants is not None and len(tenants) != ids.shape[0]:
            raise ValueError(
                f"tenants has {len(tenants)} entries for {ids.shape[0]} ids"
            )
        handles = self.submit_many(ids, tenant=tenants)
        if not handles:  # empty batch is a valid no-op (np.stack would raise)
            return np.zeros((0, 0), np.float32)
        if not self._running:
            while not handles.done() and self._drainable():
                self.flush()
        return self.results_many(handles, timeout)

    def results_many(self, handles, timeout: Optional[float] = None,
                     ) -> np.ndarray:
        """Batch consumption surface (round 22): gather a `submit_many`
        batch's rows as ONE ``[len(handles), C]`` array — the delivery
        half of the array-at-a-time host path. On a `ResultBatch` this
        waits per UNIQUE slot and broadcasts rows through the batch's
        stored inverse map (coalesced requests never re-wait, rows are
        views into the flush's logits block); any other sequence of
        handles degrades to the per-handle `result()` stack `predict`
        always did. Errors surface exactly as the scalar path would:
        the first failed request in REQUEST order raises its error."""
        if isinstance(handles, ResultBatch):
            return handles.gather(timeout)
        if not len(handles):
            return np.zeros((0, 0), np.float32)
        return np.stack([h.result(timeout) for h in handles])

    # -- flush policy -----------------------------------------------------

    def should_flush(self) -> bool:
        # lock-free probe (round 20): len() over the stripes is a sum of
        # dict lens (GIL-consistent), the head slot comes from a per-
        # stripe-locked min-arrival scan; a racing submit just makes the
        # next poll flush — the policy is a timer, not an invariant
        if not self._pending:
            return False
        if len(self._pending) >= self.config.max_batch:
            return True
        oldest = self._pending.oldest_enqueue_t()
        if oldest is None:
            return False
        return (self._clock() - oldest) * 1e3 >= self.config.max_delay_ms

    def pump(self) -> int:
        """Apply the flush policy once: flush iff ``max_batch`` or
        ``max_delay_ms`` demands it. Returns seeds dispatched (0 if the
        policy held). This is the deterministic-test / external-event-loop
        surface; the background threads just call it on a poll timer."""
        return self.flush() if self.should_flush() else 0

    # -- the three flush stages -------------------------------------------

    def _assemble(self) -> Optional[_Flush]:
        """Stage 1a (caller must hold ``_seq``): drain up to ``max_batch``
        pending slots into a new flush, fix its bucket, and — when late
        admission is on and the bucket left pad slack — PUBLISH it so
        `submit` can fill the slack until `_seal_assembled` closes it
        (typically while this flush waits for an in-flight window slot).

        Lock order (round 20): every stripe lock, THEN ``_lock`` — the
        drain must see a frozen pending queue across all stripes, and the
        striped hierarchy puts stripes strictly before the engine lock."""
        with self._pending.all_locks(), self._lock:
            if not self._pending:
                return None
            if len(self._pending) <= self.config.max_batch:
                # whole-queue drain (round 22): when everything pending
                # fits the batch, `weighted_drain_keys` is the identity
                # on the arrival-ordered queue (weights only bite on
                # overflow) and every pop's tenant bookkeeping nets to
                # empty — so one sorted merge + wholesale clear replaces
                # the per-key pop loop, bit-identically
                items = self._pending.ordered_items_unlocked()
                keys = [kv[0] for kv in items]
                slots = [kv[1] for kv in items]
                self._pending.clear_unlocked()
                self._inflight.update(items)
            else:
                keys = self._drain_keys_locked()
                slots = [self._pending.pop_unlocked(k) for k in keys]
                self._inflight.update(zip(keys, slots))
            # params snapshot: the fence in update_params guarantees no
            # swap lands while this flush is in flight, so the snapshot and
            # every drained slot's version agree
            fl = _Flush(keys, slots, self._params)
            fl.bucket = self._bucket_for(len(keys))
            self._inflight_flushes += 1
            self.stats.inflight_peak = max(
                self.stats.inflight_peak, self._inflight_flushes
            )
            jr = self.journal
            if jr.enabled:
                # the caller holds _seq, so the index _seal_assembled will
                # draw is exactly the next one
                fl.fid = self._dispatch_index + 1
                # a = the NODE id per the EVENT_KINDS contract (a
                # temporal key is a (node, t_bucket) tuple); one batched
                # ring append for the whole drain (round 20)
                jr.record_many([
                    ("assemble", slot.rid, fl.fid,
                     k[0] if isinstance(k, tuple) else k, 0)
                    for k, slot in zip(keys, slots)
                ])
                jr.emit("flush", -1, fl.fid, len(keys), fl.bucket)
            if self.config.late_admission and len(keys) < fl.bucket:
                self._open = fl
        return fl

    def _seal_assembled(self, fl: _Flush) -> None:
        """Stage 1b (caller holds ``_seq`` and a window permit): close late
        admission, then draw the dispatch index, append the dispatch-log
        entry, and consume the sampler's next key. Everything that must be
        ordered by dispatch index happens HERE — admitted seeds are already
        in ``fl.keys``, so the log and the key stream see the final batch
        composition exactly once."""
        with self._lock:
            self._open = None
        self._dispatch_index += 1
        if self.workload is not None:
            # decay-window tick on the dispatch index (caller holds _seq,
            # so tick order == seal order — replay-deterministic)
            self.workload.tick()
        self.journal.emit("seal", -1, fl.fid, len(fl.keys), fl.bucket)
        try:
            fl.seeds, extras = self._flush_arrays(fl)
            # array-native slot views (round 20): sealed composition as
            # int arrays — late admits included, addressed by slot index
            fl.ids = fl.seeds
            n_slots = len(fl.slots)
            if self.journal.enabled:
                fl.rids = np.fromiter(
                    (s.rid for s in fl.slots), np.int64, n_slots
                )
            else:
                # no journal, no rid draws: every slot carries -1
                fl.rids = np.full(n_slots, -1, np.int64)
            tix = self._tenant_ids
            tens = [s.tenant for s in fl.slots]
            uniq_tens = set(tens)
            if len(uniq_tens) == 1:
                fl.tenant_ix = np.full(
                    n_slots, tix.setdefault(uniq_tens.pop(), len(tix)),
                    np.int32,
                )
            else:
                # id assignment order == slot order, as the scalar pass
                fl.tenant_ix = np.fromiter(
                    (tix.setdefault(t, len(tix)) for t in tens),
                    np.int32, n_slots,
                )
            if self.config.max_in_flight == 1 and not extras:
                # serial mode: reuse one pad buffer per bucket (round-8
                # behavior); with in-flight > 1 each flush owns its buffer
                buf = self._seed_bufs.get((fl.bucket, fl.seeds.dtype.str))
                padded = pad_seed_batch(fl.seeds, fl.bucket, out=buf)
                self._seed_bufs[(fl.bucket, fl.seeds.dtype.str)] = padded
            else:
                padded = pad_seed_batch(fl.seeds, fl.bucket)
            if extras:
                fl.extra = tuple(
                    pad_seed_batch(e, fl.bucket) for e in extras
                )
            # round-24 epoch pin (caller holds _seq — the commit flip
            # also runs under _seq, so the stamp, the binding snapshot,
            # and the upcoming key draw are all of ONE epoch)
            fl.graph_version = self.graph_version
            if self.config.record_dispatches:
                self.dispatch_log.append(self._dispatch_log_entry(fl, padded))
                self.dispatch_graph_versions.append(fl.graph_version)
            if self._programs is not None:
                # fused path: draw the key in dispatch order, defer the
                # sample into the one-program dispatch stage; the binding
                # snapshot pins the graph arrays this flush will execute
                # against even if a zero-stall commit rebinds mid-flight
                fl.key = draw_sample_key(self._sampler)
                fl.padded = padded
                fl.binding = self._programs.binding()
            else:
                fl.ds = self._split_sample(fl, padded)
        except BaseException as exc:  # resolved (with the error) by stage 3
            fl.error = exc

    # hooks the round-19 workloads subsystem overrides (base behavior is
    # byte-identical to round 18): how flush keys become dispatch arrays,
    # what a dispatch-log entry records, and how the split path samples
    def _flush_arrays(self, fl: _Flush):
        """``(seeds int64 [n], extra per-seed arrays)`` from ``fl.keys``.
        The temporal engine's keys are ``(node, t)`` pairs and its extra
        is the query-time vector; here keys ARE the seeds."""
        return np.asarray(fl.keys, dtype=np.int64), ()

    def _dispatch_log_entry(self, fl: _Flush, padded: np.ndarray):
        return (padded.copy(), len(fl.keys))

    def _split_sample(self, fl: _Flush, padded: np.ndarray):
        return sample_batch(self._sampler, padded)

    def _dispatch(self, fl: _Flush) -> Optional[np.ndarray]:
        """Stage 2 (no engine lock held): the device work + blocking D2H —
        ONE pre-bound execute call on the fused path, the round-9
        sample(-in-assemble) + forward pair on the split path. Concurrent
        across flushes up to the window bound."""
        with self._lock:
            self.stats.dispatch_calls += 1
        self.journal.emit("dispatch", -1, fl.fid, fl.bucket)
        if fl.ds is None and self._programs is not None:
            logits = np.asarray(
                self._programs(fl.bucket, fl.params, fl.key, fl.padded,
                               *(fl.extra or ()), binding=fl.binding)
            )
            n_exec = 1
        else:
            logits = np.asarray(
                forward_logits(self._apply, fl.params, self._feature, fl.ds)
            )
            n_exec = 2  # the sample leg ran in _seal_assembled
        with self._lock:
            self.stats.execute_calls += n_exec
        self.journal.emit("execute_done", -1, fl.fid, n_exec)
        # rows of this array are handed to every waiter AND the cache;
        # read-only makes an in-place mutation by one caller a loud
        # ValueError instead of silently corrupting every later cache hit
        if logits.flags.writeable:
            logits.setflags(write=False)
        return logits

    def _resolve(self, fl: _Flush, logits: Optional[np.ndarray]) -> None:
        """Stage 3: per-flush slot resolution + cache writeback + stats.
        Safe out of dispatch order — only this flush's slots are touched.
        Always decrements the in-flight count and wakes the fence."""
        with self._lock:
            # one clock sample taken AFTER the lock is held: as the span
            # start it keeps lock-wait out of stage-overlap evidence, and
            # as the latency endpoint it keeps lock-wait IN each waiter's
            # recorded latency (their events are set after this point)
            now = t_res0 = self._clock()
            slots = fl.slots
            if (fl.error is None and slots and not slots[0].resolved
                    and slots[0].version == self.params_version
                    and not self._scalar_resolve):
                # the round-22 tentpole: whole-flush block resolution.
                # The guard is per-FLUSH, not per-slot, because both of
                # its disqualifiers are all-or-nothing: a bounded stop()
                # abandon resolves EVERY slot of the flush or none
                # (abandon_undrained clears pending+inflight under all
                # locks), and the update_params fence re-stamps versions
                # only while no flush is in flight — so slot[0] answers
                # for the batch.
                _resolve_block(self, fl, logits, now)
            else:
                for i, (k, slot) in enumerate(zip(fl.keys, fl.slots)):
                    self._inflight.pop(k, None)
                    if slot.resolved:
                        # abandoned by a bounded stop() drain: the error
                        # was delivered and the waiters counted — a late
                        # completion must not overwrite it or double-count
                        continue
                    if fl.error is None:
                        row = logits[i]
                        if slot.version == self.params_version:
                            self.cache.put(k, slot.version, row,
                                           gv=fl.graph_version)
                        slot.resolve(row)
                    else:
                        slot.resolve(None, error=fl.error)
                        self.stats.request_errors += 1
                    for t0, tenant in slot.waiters:
                        ms = (now - t0) * 1e3
                        self.stats.latency.record_ms(ms)
                        self.stats.tenant_hist(tenant).record_ms(ms)
            if fl.error is None:
                self.stats.dispatches += 1
                self.stats.dispatched_seeds += len(fl.keys)
                self.stats.padded_seeds += fl.bucket - len(fl.keys)
                self.stats.dispatch_buckets[fl.bucket] = (
                    self.stats.dispatch_buckets.get(fl.bucket, 0) + 1
                )
            self._inflight_flushes -= 1
            self._fence.notify_all()
            self.stats.spans.record("resolve", t_res0, self._clock())
            self.journal.record_many((("resolve", -1, fl.fid,
                                       len(fl.keys), 0),))

    def flush(self) -> int:
        """Dispatch up to ``max_batch`` pending unique seeds as one bucket-
        padded device batch NOW (policy bypassed). Returns the number of
        unique seeds dispatched (late-admitted ones included).

        Synchronous: assemble -> dispatch -> resolve run on the calling
        thread, and any stage error re-raises here (after resolving every
        drained slot with it). Pipelining comes from concurrent callers —
        up to ``max_in_flight`` flushes may overlap, with assembles (and
        the sampler key stream) serialized in dispatch order. The in-flight
        window permit is taken UNDER the sequencing lock, AFTER the drain:
        while a flush waits for a slot (device saturated), late-arriving
        seeds join its pad lanes; admission closes in `_seal_assembled`
        before the dispatch index and sampler key are drawn, so the log and
        key stream stay deterministic at any admission interleaving."""
        fl = None
        have_permit = False
        try:
            with self._seq:
                # spans open AFTER _seq is held, and the window wait is
                # excluded: a caller blocked behind another flush (or a
                # full window) is idle, not working, and counting the wait
                # would fake stage overlap
                t0 = self._clock()
                fl = self._assemble()
                if fl is not None:
                    self.stats.spans.record("assemble", t0, self._clock())
                if fl is None:
                    return 0
                # flush-ahead prefetch: issue the expected closure's disk
                # reads NOW, before the window wait — they land while the
                # previous flush's dispatch (and this one's window wait)
                # runs, so the gather below finds them in DRAM
                if self._prefetch_store is not None:
                    t0p = self._clock()
                    self._prefetch_flush(fl)
                    self.stats.spans.record("prefetch", t0p, self._clock())
                try:
                    jr = self.journal
                    t_w0 = self._clock() if jr.enabled else 0.0
                    self._window.acquire()
                    have_permit = True
                    if jr.enabled:
                        jr.emit("window_wait", -1, fl.fid,
                                self._clock() - t_w0)
                    t0 = self._clock()
                    self._seal_assembled(fl)  # errors land in fl.error
                    self.stats.spans.record("assemble", t0, self._clock())
                finally:
                    # _seal_assembled's first act already closed admission
                    # (it MUST happen under _lock before the key draw);
                    # this repeat only covers an interrupt landing between
                    # the window acquire and the seal
                    with self._lock:
                        self._open = None
            logits = None
            if fl.error is None:
                t0 = self._clock()
                try:
                    logits = self._dispatch(fl)
                except BaseException as exc:
                    fl.error = exc
                t1 = self._clock()
                self.stats.spans.record("dispatch", t0, t1)
                if self.workload is not None:
                    # per-flush width + latency (owner 0: this engine is
                    # the only "owner" at single-host grain)
                    self.workload.observe_flush(0, len(fl.keys), t1 - t0)
            self._resolve(fl, logits)  # records its own post-lock span
            if fl.error is not None:
                raise fl.error
            return len(fl.keys)
        finally:
            if have_permit:
                self._window.release()

    def _bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    def _drain_keys_locked(self) -> List[int]:
        # materialize the striped store as one arrival-ordered dict: the
        # weighted drain sees exactly the FIFO the round-15 single-dict
        # queue presented (slot.seq is the global arrival stamp)
        return weighted_drain_keys(
            self._pending.ordered_dict_unlocked(),
            self.config.max_batch, self.config.tenant_weights,
        )

    def _drainable(self) -> bool:
        return bool(self._pending)

    # -- flush-ahead prefetch (round 18, ROADMAP item 3a) ------------------

    def _on_prefetch_event(self, kind: str, n: int) -> None:
        """Staging-buffer tap: mirrors consumption/waste into ServeStats
        and the journal (plain ints under the GIL — the ServeStats
        discipline). ``hit`` fires at gather time, which may be a
        different flush than the issuer, so the event carries no fid."""
        if kind == "hit":
            self.stats.tier_prefetch_hit += n
            self.journal.emit("prefetch_hit", -1, -1, n)
        elif kind == "wasted":
            self.stats.tier_prefetch_wasted += n

    def prefetch_seeds(self, seed_ids, fid: int = -1) -> int:
        """Issue flush-ahead disk reads for the expected k-hop closure
        of ``seed_ids`` (OBSERVE-ONLY: no key consumed, no placement
        moved, no served bit changed — see ``ServeConfig.tier_prefetch``).
        Returns rows issued. The engine calls this itself at assemble
        time; `DistServeEngine` calls it per owner off the routed
        sub-batches, one window earlier still. Dedup in the staging
        buffer makes the double-issue free."""
        store = self._prefetch_store
        if store is None:
            return 0
        from ..tiers import expected_closure

        hops = self.config.tier_prefetch_hops
        if hops is None:
            hops = len(self._sampler.sizes)
        nodes = expected_closure(
            self._sampler, np.asarray(seed_ids, np.int64), hops,
            max_nodes=self.config.tier_prefetch_max_rows,
        )
        if nodes.size == 0:
            return 0
        stored = self._tier_feature.stored_rows_of(nodes)
        issued = store.prefetch_rows(stored[stored >= 0])
        if issued:
            self.stats.tier_prefetch_issued += issued
            self.journal.emit("prefetch_issue", -1, fid, issued,
                              int(nodes.size))
        return issued

    def _prefetch_flush(self, fl: "_Flush") -> None:
        """Assemble-time prefetch for a drained flush (called under
        ``_seq``, before the window wait — the reads overlap the
        PREVIOUS flush's dispatch). With ``tier_prefetch_at="submit"``
        this is the catch-all for seeds the submit-time walk missed
        (late admits, window flushes). Never fails a flush: prefetch is
        a hint, and any error here would break the on/off parity pin."""
        if self._prefetch_store is None:
            return
        keys = fl.keys
        if self._pf_walked:
            missed = [k for k in keys if k not in self._pf_walked]
            if not missed:
                return
            keys = missed
        try:
            self.prefetch_seeds(keys, fid=fl.fid)
        except Exception:
            pass

    def _cancel_prefetch(self) -> None:
        """Fence hook: drop staged prefetch rows (counted as wasted).
        Callers hold the fence (no gather in flight), so nothing races
        the staging map. The submit-walk memo clears with it — staged
        rows are gone, so "already walked" no longer implies "already
        staged"."""
        self._pf_walked = frozenset()
        if self._prefetch_store is not None:
            self._prefetch_store.cancel_prefetch()

    def reset_stats(self) -> None:
        """Zero every counter/histogram AND re-point the embedding cache's
        counter at the fresh `ServeStats` (the two must move together — a
        bare ``stats.__init__()`` would leave the cache counting into the
        detached old object). The journal ring is cleared with it — stale
        lifecycle events would make breakdowns straddle the reset. Benches
        call this after their warm-up pass; cache CONTENTS are untouched
        (use `cache.invalidate()` for that). Registry adapters registered
        by `register_metrics` follow the swap (they resolve through
        ``self.stats`` at read time)."""
        with self._lock:
            self.stats = ServeStats()
            self.cache.counters = self.stats.cache
            if self.journal.enabled:
                self.journal.clear()
            if self.workload is not None:
                # same straddle rule as the journal: sketch/owner state
                # from before the reset would skew every report after it
                self.workload.clear()

    # -- observability surface --------------------------------------------

    def register_metrics(self, registry: Optional[MetricsRegistry] = None,
                         prefix: str = "quiver_serve",
                         labels: Optional[Dict[str, str]] = None,
                         ) -> MetricsRegistry:
        """Adapt this engine's live state into a `trace.MetricsRegistry`
        (created when not given): every `ServeStats` counter as a
        callback-backed counter, the engine's QUEUE-STATE gauges (pending
        depth, in-flight flushes/window/peak, cache rows, params version),
        per-bucket dispatch counts (``bucket`` label), the embedding
        cache's hit/miss/eviction family, and the live latency histogram.
        Adapters READ the engine at exposition time — nothing is counted
        twice, and `reset_stats` swaps are followed. Returns the
        registry (``registry.to_prometheus()`` /
        ``registry.snapshot()`` are the export surfaces)."""
        reg = registry if registry is not None else MetricsRegistry()
        for f in ("requests", "coalesced", "dispatches", "dispatched_seeds",
                  "padded_seeds", "dispatch_calls", "execute_calls",
                  "late_admitted", "tier_promoted", "tier_demoted",
                  "placement_batches", "tier_prefetch_issued",
                  "tier_prefetch_hit", "tier_prefetch_wasted",
                  "shed", "request_errors",
                  "undrained", "graph_deltas", "delta_edges",
                  "delta_tile_writes", "delta_tile_spills",
                  "delta_cache_invalidated", "edges_deleted",
                  "edges_expired", "tiles_reclaimed", "compactions"):
            reg.counter_fn(f"{prefix}_{f}_total",
                           (lambda f=f: getattr(self.stats, f)),
                           f"ServeStats.{f}", labels)
        register_tenant_latency(
            reg, prefix, "end-to-end request latency by submitting tenant",
            lambda: self.stats, self.config.tenant_weights, labels,
        )
        reg.gauge_fn(f"{prefix}_pending_depth",
                     lambda: len(self._pending),
                     "unique seeds queued and not yet drained", labels)
        reg.gauge_fn(f"{prefix}_inflight_flushes",
                     lambda: self._inflight_flushes,
                     "flushes between assemble and resolve now", labels)
        reg.gauge_fn(f"{prefix}_inflight_window",
                     lambda: self.config.max_in_flight,
                     "configured max_in_flight bound", labels)
        reg.gauge_fn(f"{prefix}_inflight_peak",
                     lambda: self.stats.inflight_peak,
                     "largest in-flight occupancy observed", labels)
        reg.gauge_fn(f"{prefix}_cache_rows", lambda: len(self.cache),
                     "embedding-cache resident rows", labels)
        reg.gauge_fn(f"{prefix}_params_version",
                     lambda: self.params_version,
                     "current weights version", labels)
        reg.gauge_fn(f"{prefix}_graph_version",
                     lambda: self.graph_version,
                     "fenced streaming-graph delta commits applied",
                     labels)
        reg.gauge_fn(f"{prefix}_delta_pending_edges",
                     lambda: (len(self.pending_delta)
                              if self.pending_delta is not None else 0),
                     "edge arrivals staged and not yet committed", labels)
        register_stream_reserve(
            reg, prefix, lambda: getattr(self._sampler, "stream", None),
            labels,
        )
        reg.gauge_fn(f"{prefix}_placement_version",
                     lambda: self.placement_version,
                     "fenced tier-placement batches applied", labels)
        reg.gauge_fn(f"{prefix}_tier_adapt_errors",
                     lambda: self.tier_adapt_errors,
                     "failed background tier-adaptation passes", labels)
        reg.gauge_fn(f"{prefix}_compact_errors",
                     lambda: self.compact_errors,
                     "failed background compaction passes", labels)
        reg.gauge_fn(f"{prefix}_retention_errors",
                     lambda: self.retention_errors,
                     "failed wall-clock TTL retention passes", labels)
        reg.gauge_fn(f"{prefix}_retention_passes",
                     lambda: self.retention_passes,
                     "completed wall-clock TTL retention passes", labels)
        reg.gauge_fn(
            f"{prefix}_tier_prefetch_hit_rate",
            lambda: (self.stats.tier_prefetch_hit
                     / max(self.stats.tier_prefetch_issued, 1)),
            "flush-ahead prefetch rows consumed over rows issued", labels)
        if self._tier_feature is not None:
            reg.gauge_fn(
                f"{prefix}_tier_hbm_rows",
                lambda: self._tier_feature.tier_store.placement.counts()["hbm"],
                "rows resident in HBM under the adaptive placement", labels)
            reg.gauge_fn(
                f"{prefix}_tier_host_rows",
                lambda: self._tier_feature.tier_store.placement.counts()["host"],
                "rows resident in host DRAM under the adaptive placement",
                labels)
        reg.gauge_fn(f"{prefix}_journal_events", lambda: len(self.journal),
                     "lifecycle events in the journal ring", labels)
        for b in self._buckets:
            reg.counter_fn(
                f"{prefix}_bucket_dispatches_total",
                (lambda b=b: self.stats.dispatch_buckets.get(b, 0)),
                "resolved dispatches by bucket shape",
                dict(labels or {}, bucket=str(b)),
            )
        register_hit_rate(reg, f"{prefix}_cache", lambda: self.stats.cache,
                          labels)
        reg.histogram(f"{prefix}_latency_ms",
                      "end-to-end request latency (submit -> resolve)",
                      labels, fn=lambda: self.stats.latency)
        reg.histogram(f"{prefix}_commit_stall_us",
                      "per-commit serving stall, µs (fenced: whole "
                      "drain; zero-stall: the _seq flip hold)",
                      labels, fn=lambda: self.stats.commit_stall)
        if self.workload is not None:
            self.workload.register_metrics(
                reg, prefix=f"{prefix}_workload", labels=labels, owners=(0,)
            )
        return reg

    def export_chrome_trace(self, path: str, extra_sources: Sequence = (),
                            metadata: Optional[Dict[str, object]] = None,
                            ) -> Dict[str, object]:
        """Write a Perfetto/chrome://tracing-loadable ``trace_events``
        timeline merging the engine's stage spans (``stats.spans``) and —
        when journaling is on — the request-lifecycle journal (per-flush
        lanes show overlapped in-flight flushes side by side). Spans and
        journal share the engine clock, so the merge is one timeline, not
        two guesses. ``extra_sources`` appends more (name, SpanRecorder |
        EventJournal) pairs recorded on the same clock (e.g.
        `comm` exchange spans)."""
        sources: List = [("serve.spans", self.stats.spans)]
        if self.journal.enabled:
            sources.append(("serve.journal", self.journal))
        if self._commit_samples:
            # round-24 counter lane: graph_version staircase + per-commit
            # stall alongside the flush lanes
            sources.append(
                ("serve.commits",
                 _CommitCounterSource(self._commit_samples))
            )
        if self.workload is not None and self.workload.counters is not None:
            # the round-13 counter lane: sampled workload series (head
            # coverage, observed seeds) graph under the flush lanes
            sources.append(("serve.workload", self.workload.counters))
        sources.extend(extra_sources)
        return _export_chrome_trace(path, sources, metadata)

    # -- warmup -----------------------------------------------------------

    def _warmup_sampler(self):
        """A twin of the serving sampler (same topology/seed/config) for
        warmup traffic, so pre-tracing consumes the TWIN's key stream and
        the serving stream + replay log stay untouched. None when the
        sampler doesn't support the share_ipc/lazy_from_ipc_handle clone
        protocol."""
        s = self._sampler
        try:
            return type(s).lazy_from_ipc_handle(s.share_ipc())
        except Exception:
            return None

    def warmup(self, buckets: Optional[Sequence[int]] = None) -> Dict[int, float]:
        """Bind the compiled program for every bucket shape so the first
        REAL request at each bucket doesn't eat a compile. Returns
        {bucket: seconds}.

        Fused engines AOT-compile one LOADED executable per bucket
        (``jax.jit(...).lower(...).compile()`` via
        `inference.BucketPrograms`) — no jit cache warmed, no dispatch
        executed, NO key consumed (lowering traces abstract values only) —
        and then SEAL the program table: a post-warmup bucket miss raises
        RuntimeError instead of silently compiling for 12–60 s under a live
        request. Split engines keep the round-9 behavior: one warm dispatch
        per bucket through a twin sampler when the sampler supports cloning
        (key stream untouched); otherwise through the serving sampler under
        the sequencing lock with an ``n_valid=0`` dispatch-log entry, so a
        parity replay still consumes the same key indices."""
        buckets = self._buckets if buckets is None else tuple(
            sorted(int(b) for b in buckets)
        )
        with self._lock:
            params = self._params
        times: Dict[int, float] = {}
        if self._programs is not None:
            for b in buckets:
                t0 = time.perf_counter()
                self._programs.compile_bucket(b, params)
                times[b] = time.perf_counter() - t0
            self._programs.seal()
            return times
        twin = self._warmup_sampler()
        for b in buckets:
            padded = np.zeros(b, np.int64)
            t0 = time.perf_counter()
            if twin is not None:
                ds = sample_batch(twin, padded)
            else:
                with self._seq:
                    self._dispatch_index += 1
                    if self.config.record_dispatches:
                        self.dispatch_log.append((padded.copy(), 0))
                        self.dispatch_graph_versions.append(
                            self.graph_version)
                    ds = sample_batch(self._sampler, padded)
            np.asarray(forward_logits(self._apply, params, self._feature, ds))
            times[b] = time.perf_counter() - t0
        return times

    # -- weight updates ---------------------------------------------------

    def update_params(self, params) -> None:
        """Install new weights behind a FENCE: block new assembles (the
        sequencing lock), wait for every in-flight flush to resolve, then
        bump ``params_version`` and invalidate the embedding cache — so no
        served logit ever crosses a weight update mid-flush. Pending (not
        yet dispatched) slots are re-stamped to the new version — their
        flush will compute under the new weights. Requests resolved by the
        drained in-flight flushes were accepted under the old weights and
        keep their old-version results (never cached past the bump).

        Lock order (round 20): stripes before ``_lock`` — the fence wait
        releases only ``_lock`` while the stripe locks stay held, so
        submits park at stripe acquire (holding nothing) and resolves
        (which need only ``_lock``) drain freely: no cycle."""
        with self._seq:
            with self._pending.all_locks():
                with self._fence:
                    while self._inflight_flushes:
                        self._fence.wait()
                    # a prefetch issued for a pre-fence flush may still be
                    # in flight: drop the staging (bytes stay valid
                    # forever, but the rows' consumers are gone — holding
                    # them would only skew waste accounting). Never blocks
                    # on the pool.
                    self._cancel_prefetch()
                    self._params = params
                    self.params_version += 1
                    self.cache.invalidate()
                    for slot in self._pending.values_unlocked():
                        slot.version = self.params_version

    # -- streaming graph deltas (round 17; quiver_tpu.stream) --------------

    def stage_edges(self, src, dst, ts=None) -> int:
        """Accumulate edge arrivals host-side into ``pending_delta``
        (observe-only until a commit: no device state, no fence, no
        served bit moves). Edge ids are validated HERE, against the
        bound stream's node range, so one bad arrival raises at the
        staging call site and never poisons the pending buffer (a commit
        failure re-stages the delta — an unvalidated bad edge would
        wedge every future ``update_graph``). Returns the pending-edge
        count — the ``delta_pending_edges`` gauge reads the same
        number."""
        from ..stream import GraphDelta, validate_edge_ids

        stream = getattr(self._sampler, "stream", None)
        if stream is not None:
            n = stream.n
        else:
            # not stream-bound (yet): validate against the sampler's own
            # graph so a bad arrival still cannot poison the buffer — a
            # later bind_stream + commit would otherwise wedge on it
            topo = getattr(self._sampler, "csr_topo", None)
            n = topo.node_count if topo is not None else None
        src, dst = validate_edge_ids(src, dst, n, "staged")
        if stream is not None:
            # the temporal-arity contract holds AT THE STAGING CALL SITE
            # in BOTH directions: a ts-less arrival on a temporal stream
            # — or a timestamped one on a plain stream — must raise here,
            # because a delta that can never commit would re-stage on
            # every update_graph failure and wedge the pending buffer
            # forever (and poison later correct stagings via GraphDelta's
            # homogeneity check)
            if getattr(stream, "temporal", False):
                if (ts is None
                        or np.asarray(ts).reshape(-1).shape != src.shape):
                    raise ValueError(
                        "temporal stream needs one ts per staged edge"
                    )
            elif ts is not None:
                raise ValueError(
                    "edge timestamps staged into a non-temporal stream — "
                    "build StreamingTiledGraph(edge_ts=...) to carry them"
                )
        with self._lock:
            if self.pending_delta is None:
                self.pending_delta = GraphDelta()
            self.pending_delta.add_edges(src, dst, ts=ts)
            n = len(self.pending_delta)
        self.journal.emit("graph_delta", -1, -1, n)
        return n

    def stage_removals(self, src, dst) -> int:
        """Accumulate edge DELETIONS host-side into ``pending_delta``
        (round 21) — the removal side of `stage_edges`: validated here
        against the bound stream's node range so one bad id raises at
        the call site, applied at the next `update_graph` commit as
        masked lane rewrites (survivors shift left — a delete-then-
        replay is bit-identical to a graph built without the edge).
        EXISTENCE is checked at commit preflight, not here: the edge may
        legitimately be in the same pending batch (append then remove in
        one commit is valid and nets out). Returns the pending count."""
        from ..stream import GraphDelta, validate_edge_ids

        stream = getattr(self._sampler, "stream", None)
        if stream is not None:
            n = stream.n
        else:
            topo = getattr(self._sampler, "csr_topo", None)
            n = topo.node_count if topo is not None else None
        src, dst = validate_edge_ids(src, dst, n, "removed")
        with self._lock:
            if self.pending_delta is None:
                self.pending_delta = GraphDelta()
            self.pending_delta.remove_edges(src, dst)
            n = len(self.pending_delta)
        self.journal.emit("graph_delta", -1, -1, n)
        return n

    def stage_updates(self, src, dst, ts) -> int:
        """Accumulate per-edge TIMESTAMP REWRITES into ``pending_delta``
        (round 21): each (src, dst) must exist at commit time and gets
        its ts lane overwritten in place — no lane moves, no degree
        change, so only the recency weighting of future draws shifts.
        Temporal streams only (the ts lane is the one mutable per-edge
        payload); ``ts`` must be finite (+inf is the retention expiry
        sentinel). Returns the pending count."""
        from ..stream import GraphDelta, validate_edge_ids

        stream = getattr(self._sampler, "stream", None)
        if stream is not None:
            n = stream.n
            if not getattr(stream, "temporal", False):
                raise ValueError(
                    "timestamp updates need a temporal stream "
                    "(StreamingTiledGraph(edge_ts=...)) — plain streamed "
                    "tiles carry no per-edge payload to rewrite"
                )
        else:
            topo = getattr(self._sampler, "csr_topo", None)
            n = topo.node_count if topo is not None else None
        src, dst = validate_edge_ids(src, dst, n, "updated")
        with self._lock:
            if self.pending_delta is None:
                self.pending_delta = GraphDelta()
            self.pending_delta.update_edges(src, dst, ts)
            n = len(self.pending_delta)
        self.journal.emit("graph_delta", -1, -1, n)
        return n

    def update_graph(self, delta=None, *, installs=None,
                     invalidate=None) -> Dict[str, object]:
        """Commit a graph delta behind the SAME fence as `update_params`:
        block new assembles (the sequencing lock), drain every in-flight
        flush, apply the batch to the bound `stream.StreamingTiledGraph`
        (host pad-lane writes / tile spills + ONE batched device tile
        swap), bump ``graph_version``, rebind the sealed AOT programs'
        graph/table arguments (`BucketPrograms.rebind` — same shapes, no
        recompile), and invalidate exactly the embedding-cache entries
        whose k-hop closure touched a delta row (the versioned-node-stamp
        rule; ``invalidate=`` overrides with a precomputed set — the dist
        router passes the fleet-global closure). After the fence, when
        the engine has an adaptive tier store + workload telemetry and
        ``stream_adapt_tiers`` is on, one `adapt_tiers` pass runs so a
        delta-hot subgraph pulls its rows off disk NOW (round-17
        consumer (c)).

        ``delta=None`` commits (and clears) ``pending_delta``. An empty
        commit is a strict no-op — no fence, no version bump, no bit
        moves: frozen-graph replay == delta-replay with an empty delta,
        pinned in tests/test_stream.py. The appended edges are visible to
        the next sample after this returns (copy-all semantics: a draw
        with fanout >= degree must include them).

        Round 21 — the same fenced commit also carries the LIFECYCLE
        flows: staged removals rewrite their nodes' lanes in place
        (delete-then-replay == built-without-the-edge, bit for bit),
        staged ts updates overwrite payload lanes, TTL retention (when
        ``stream_retention_window`` > 0 on a temporal stream) expires
        every edge older than the commit clock minus the window as
        masked ``ts -> +inf`` lane writes, and a `StreamCapacityError`
        triggers one reactive bank grow + sealed-program rebuild when
        ``stream_provision_tiles`` > 0. All under ONE fence, one version
        bump, one closure-exact invalidation pass.

        Round 24 — with ``fenced_commits=False`` (the default) the same
        commit is ZERO-STALL: the post-commit device arrays build fully
        off-fence (``stream.apply(defer_publish=True)``), then flip under
        ``_seq`` only — no in-flight drain. Flushes already in flight
        complete against the immutable old arrays their seal pinned
        (epoch pinning); the fence's three consumers go version-aware
        (cache graph-version floors via `EmbeddingCache.raise_floor`,
        post-flip replica retire in the router, post-flip adapt_tiers).
        The visibility contract is unchanged: the delta is visible to
        every flush sealed after this returns; a flush racing the commit
        legitimately serves whichever epoch its seal landed in, and logs
        it in ``dispatch_graph_versions``. Re-provisioning (a shape
        change) always takes the full fenced path — a sealed executable
        rebuild cannot overlap an in-flight flush bound to the old
        shapes."""
        stream = getattr(self._sampler, "stream", None)
        if stream is None:
            raise ValueError(
                "update_graph needs a stream-bound sampler — build a "
                "stream.StreamingTiledGraph over the topology and call "
                "sampler.bind_stream(stream) before constructing the "
                "engine"
            )
        from_pending = delta is None
        with self._lock:
            if delta is None:
                delta, self.pending_delta = self.pending_delta, None
        n_edges = 0 if delta is None else len(delta)
        if n_edges == 0 and not installs:
            return {"edges": 0, "installs": 0, "cache_invalidated": 0,
                    "affected_seeds": 0, "graph_version": self.graph_version}
        if self.config.fenced_commits:
            return self._update_graph_fenced(stream, delta, installs,
                                             invalidate, n_edges,
                                             from_pending)
        return self._update_graph_zerostall(stream, delta, installs,
                                            invalidate, n_edges,
                                            from_pending)

    def _update_graph_fenced(self, stream, delta, installs, invalidate,
                             n_edges, from_pending) -> Dict[str, object]:
        """The round-17..23 drain-ordered commit, bit-identical — the
        ``fenced_commits=True`` parity twin (and the fallback every
        re-provisioning commit takes in either mode)."""
        from ..stream import StreamCapacityError

        applied = False
        provisioned = False
        expired = None
        try:
            with self._seq:
                t_stall0 = self._clock()
                with self._fence:
                    while self._inflight_flushes:
                        self._fence.wait()
                    # graph deltas change the expected closure: staged
                    # prefetch rows keep valid bytes but stale intent —
                    # drop them with the other fence consumers
                    self._cancel_prefetch()
                    try:
                        summary = stream.apply(delta, installs=installs)
                    except StreamCapacityError:
                        if self.config.stream_provision_tiles <= 0:
                            raise
                        # reactive re-provisioning (round 21): grow the
                        # bank by one configured increment and retry the
                        # SAME batch once — one sealed-program rebuild
                        # below, never recompile-per-commit. A second
                        # failure propagates (the batch outgrows even the
                        # grown bank; the caller sizes the increment).
                        stream.provision_reserve(
                            self.config.stream_provision_tiles
                        )
                        provisioned = True
                        summary = stream.apply(delta, installs=installs)
                    applied = True
                    self.graph_version += 1
                    # TTL retention (round 21): expire at the commit
                    # clock, under the SAME fence as the delta it rides —
                    # the cutoff is a pure f32 function of committed
                    # timestamps (lifecycle.RetentionPolicy), so replicas
                    # fed the same commit stream expire identical lanes
                    if (self.retention is not None
                            and getattr(stream, "temporal", False)):
                        cut = self.retention.cutoff_for(delta.max_ts())
                        if cut is not None:
                            exp = stream.expire_edges(cut)
                            self.retention.mark_expired(cut)
                            if exp["edges_expired"]:
                                expired = exp
                                self.stats.edges_expired += (
                                    exp["edges_expired"]
                                )
                            summary["edges_expired"] = exp["edges_expired"]
                            summary["retention_cutoff"] = cut
                    if self._programs is not None:
                        # sealed executables take the graph/table as
                        # ARGUMENTS: swap same-shaped arrays, never
                        # recompile. The table is re-read only for
                        # features with a dynamic jit spec
                        # (ClosureFeature installs); a plain table never
                        # changes under a topology delta.
                        table = imap = None
                        if hasattr(self._feature, "jit_gather_spec"):
                            from ..inference import feature_gather_spec

                            table, imap = feature_gather_spec(self._feature)
                        if provisioned:
                            # shapes changed at the provision event: the
                            # one sanctioned rebuild (reprovision swaps
                            # the spec's graph avals and recompiles the
                            # warmed buckets through the process cache).
                            # _params is read bare: the fence Condition
                            # wraps _lock, so it is already held here
                            self._programs.reprovision(
                                self._sampler.fused_graph_arrays(),
                                params=self._params,
                            )
                            if table is not None:
                                self._programs.rebind(table=table,
                                                      index_map=imap)
                        else:
                            self._programs.rebind(
                                graph=self._sampler.fused_graph_arrays(),
                                table=table, index_map=imap,
                            )
                    # invalidation seeds: every staged source (appends +
                    # removals + updates via delta.sources()) UNION the
                    # retention-expired sources — expiry changed those
                    # rows' draws under this same fence, so their reverse
                    # closure is stale too
                    if invalidate is not None:
                        affected = np.asarray(list(invalidate), np.int64)
                        if expired is not None:
                            hops = self.config.stream_invalidate_hops
                            if hops is None:
                                hops = max(len(self._sampler.sizes) - 1, 0)
                            affected = np.union1d(
                                affected,
                                stream.affected_seeds(expired["sources"],
                                                      hops),
                            )
                    else:
                        srcs = (np.asarray(delta.sources(), np.int64)
                                if n_edges else np.array([], np.int64))
                        if expired is not None:
                            srcs = np.union1d(srcs, expired["sources"])
                        if srcs.size:
                            hops = self.config.stream_invalidate_hops
                            if hops is None:
                                hops = max(len(self._sampler.sizes) - 1, 0)
                            affected = stream.affected_seeds(srcs, hops)
                        else:
                            affected = np.array([], np.int64)
                    # invalidate by NODE, not exact key: temporal cache
                    # entries are (node, t)-keyed, and a changed row
                    # staleness-taints every cached t of an affected seed
                    # (for plain int keys this is behavior-identical to
                    # the round-17 invalidate_keys)
                    invalidated = self.cache.invalidate_nodes(
                        int(x) for x in affected
                    )
                    self.stats.graph_deltas += 1
                    self.stats.delta_edges += n_edges
                    self.stats.delta_tile_writes += summary["pad_writes"]
                    self.stats.delta_tile_spills += summary["tile_spills"]
                    self.stats.delta_cache_invalidated += invalidated
                    self.stats.edges_deleted += summary.get(
                        "edges_deleted", 0
                    )
                    # µs, observe-only: the whole drain + fenced work is
                    # serving stall in this mode (nothing seals under it)
                    t_now = self._clock()
                    stall_us = (t_now - t_stall0) * 1e6
                    self.stats.commit_stall.record_ms(stall_us)
                    self._commit_samples.append(
                        ("graph_version", t_now, self.graph_version))
                    self._commit_samples.append(
                        ("commit_stall_us", t_now, stall_us))
        except BaseException:
            # `stream.apply` is atomic (preflight before any mutation),
            # so a commit that raised BEFORE apply returned left the
            # graph untouched — re-stage a pending-sourced delta so the
            # staged edges survive the failure (ahead of anything staged
            # meanwhile: arrival order is the replay order). A failure
            # AFTER apply (e.g. an interrupt mid-invalidation) must NOT
            # re-stage: the edges are committed, and replaying them
            # would double-append
            if from_pending and n_edges and not applied:
                with self._lock:
                    if self.pending_delta is not None:
                        delta.extend(self.pending_delta)
                    self.pending_delta = delta
            raise
        self.journal.emit("delta_commit", -1, self.graph_version,
                          n_edges, invalidated)
        if summary.get("edges_deleted"):
            self.journal.emit("edge_delete", -1, self.graph_version,
                              summary["edges_deleted"])
        if expired is not None:
            self.journal.emit("retention_expire", -1, self.graph_version,
                              expired["edges_expired"], expired["nodes"])
        summary["cache_invalidated"] = invalidated
        summary["provisioned"] = provisioned
        summary["affected_seeds"] = int(affected.size)
        summary["graph_version"] = self.graph_version
        if (self.config.stream_adapt_tiers
                and self._tier_feature is not None
                and self.workload is not None):
            # consumer (c): re-place tiers at the commit (adapt_tiers
            # takes its own fence; a failing pass is counted, never fatal
            # — the tier-daemon contract)
            try:
                summary["tier_adapt"] = self.adapt_tiers()
            except Exception:
                self.tier_adapt_errors += 1
        return summary

    def _update_graph_zerostall(self, stream, delta, installs, invalidate,
                                n_edges, from_pending) -> Dict[str, object]:
        """Round-24 tentpole: build everything off-fence, flip under
        ``_seq`` only. Phases:

        1. BUILD (commit lock, no fence): ``stream.apply(...,
           defer_publish=True)`` mutates host mirrors and stages the
           post-commit device arrays without touching what `graph()`
           serves; retention expiry stages into the same flip; the
           affected-closure set is computed from the updated host
           adjacency. Traffic seals and dispatches throughout.
        2. FLIP (``_seq`` only — the measured stall): `stream.publish()`
           (an O(1) ref swap), the ``graph_version`` bump, `rebind` of
           the sealed programs' graph arguments, prefetch-intent drop.
           A flush sealing before the flip pinned the old binding and
           stamped the old version; one sealing after gets the new —
           never a mix (the stamp, the binding snapshot and the key draw
           share one ``_seq`` hold in `_seal_assembled`).
        3. POST-FLIP (no fence): the closure-touched nodes' cache
           graph-version floors rise (`EmbeddingCache.raise_floor` —
           eager drop of resident old-epoch entries plus the writeback
           gate that stops an old-epoch in-flight flush from
           re-inserting a stale row after it resolves), stats/journal,
           and the deferred adapt_tiers pass.

        In-flight correctness is the round-11 jit-argument rule: sealed
        executables take the graph as ARGUMENTS and the stream's device
        sync copies on write (`_scatter_rows`), so the old array objects
        a flush pinned are immutable — it completes bit-exactly against
        its epoch, and `replay_fleet_oracle(graph_version=...)` proves
        it row by row. A `StreamCapacityError` (shape change needed)
        falls back to the FULL fenced commit: reprovisioning swaps the
        executables' graph avals, which an in-flight flush bound to the
        old shapes must not straddle."""
        from ..stream import StreamCapacityError

        applied = False
        expired = None
        try:
            with self._commit_lock:
                try:
                    summary = stream.apply(delta, installs=installs,
                                           defer_publish=True)
                except StreamCapacityError:
                    # atomic apply: nothing moved — re-run the whole
                    # commit fenced (it provisions + retries when
                    # configured, or re-raises the capacity error)
                    return self._update_graph_fenced(
                        stream, delta, installs, invalidate, n_edges,
                        from_pending,
                    )
                applied = True
                new_version = self.graph_version + 1
                if (self.retention is not None
                        and getattr(stream, "temporal", False)):
                    cut = self.retention.cutoff_for(delta.max_ts())
                    if cut is not None:
                        exp = stream.expire_edges(cut, defer_publish=True)
                        self.retention.mark_expired(cut)
                        if exp["edges_expired"]:
                            expired = exp
                        summary["edges_expired"] = exp["edges_expired"]
                        summary["retention_cutoff"] = cut
                # invalidation closure, off-fence: the host adjacency is
                # already post-commit (only the device publish defers),
                # so this is the same set the fenced twin computes
                if invalidate is not None:
                    affected = np.asarray(list(invalidate), np.int64)
                    if expired is not None:
                        hops = self.config.stream_invalidate_hops
                        if hops is None:
                            hops = max(len(self._sampler.sizes) - 1, 0)
                        affected = np.union1d(
                            affected,
                            stream.affected_seeds(expired["sources"],
                                                  hops),
                        )
                else:
                    srcs = (np.asarray(delta.sources(), np.int64)
                            if n_edges else np.array([], np.int64))
                    if expired is not None:
                        srcs = np.union1d(srcs, expired["sources"])
                    if srcs.size:
                        hops = self.config.stream_invalidate_hops
                        if hops is None:
                            hops = max(len(self._sampler.sizes) - 1, 0)
                        affected = stream.affected_seeds(srcs, hops)
                    else:
                        affected = np.array([], np.int64)
                table = imap = None
                if (self._programs is not None
                        and hasattr(self._feature, "jit_gather_spec")):
                    from ..inference import feature_gather_spec

                    table, imap = feature_gather_spec(self._feature)
                # ---- the flip: the only serving-visible moment
                with self._seq:
                    t_stall0 = self._clock()
                    stream.publish()
                    self.graph_version = new_version
                    if self._programs is not None:
                        self._programs.rebind(
                            graph=self._sampler.fused_graph_arrays(),
                            table=table, index_map=imap,
                        )
                    self._cancel_prefetch()
                    stall_us = (self._clock() - t_stall0) * 1e6
                # ---- post-flip deferred passes
                invalidated = self.cache.raise_floor(
                    (int(x) for x in affected), new_version
                )
                with self._lock:
                    if expired is not None:
                        self.stats.edges_expired += (
                            expired["edges_expired"]
                        )
                    self.stats.graph_deltas += 1
                    self.stats.delta_edges += n_edges
                    self.stats.delta_tile_writes += summary["pad_writes"]
                    self.stats.delta_tile_spills += summary["tile_spills"]
                    self.stats.delta_cache_invalidated += invalidated
                    self.stats.edges_deleted += summary.get(
                        "edges_deleted", 0
                    )
                    self.stats.commit_stall.record_ms(stall_us)
                    t_now = self._clock()
                    self._commit_samples.append(
                        ("graph_version", t_now, new_version))
                    self._commit_samples.append(
                        ("commit_stall_us", t_now, stall_us))
        except BaseException:
            # same re-stage rule as the fenced twin: apply is atomic, so
            # a pre-apply failure leaves the staged edges recoverable
            if from_pending and n_edges and not applied:
                with self._lock:
                    if self.pending_delta is not None:
                        delta.extend(self.pending_delta)
                    self.pending_delta = delta
            raise
        self.journal.emit("delta_commit", -1, self.graph_version,
                          n_edges, invalidated)
        if summary.get("edges_deleted"):
            self.journal.emit("edge_delete", -1, self.graph_version,
                              summary["edges_deleted"])
        if expired is not None:
            self.journal.emit("retention_expire", -1, self.graph_version,
                              expired["edges_expired"], expired["nodes"])
        summary["cache_invalidated"] = invalidated
        summary["provisioned"] = False
        summary["affected_seeds"] = int(affected.size)
        summary["graph_version"] = self.graph_version
        summary["commit_stall_us"] = stall_us
        if (self.config.stream_adapt_tiers
                and self._tier_feature is not None
                and self.workload is not None):
            # consumer (c), now an explicitly post-flip deferred pass
            try:
                summary["tier_adapt"] = self.adapt_tiers()
            except Exception:
                self.tier_adapt_errors += 1
        return summary

    # -- graph lifecycle (round 21; quiver_tpu.lifecycle) ------------------

    def expire_edges(self, t_commit=None) -> Dict[str, object]:
        """Run TTL retention NOW, off the commit path: advance the
        retention clock to ``t_commit`` (None keeps the clock where the
        last commit left it) and expire every edge older than
        ``clock - window`` behind the `update_params` fence — masked
        ``ts -> +inf`` lane writes, one version bump, closure-exact
        invalidation of the expired rows' reverse k-hop closure. The
        commit path runs this automatically; this entry point is for
        wall-clock-driven expiry between commits (e.g. a quiet stream
        whose window keeps sliding). Returns the stream's expiry summary
        plus ``cache_invalidated``/``graph_version``."""
        stream = getattr(self._sampler, "stream", None)
        if stream is None or not getattr(stream, "temporal", False):
            raise ValueError(
                "retention expiry needs a temporal stream-bound sampler "
                "(StreamingTiledGraph(edge_ts=...) + bind_stream)"
            )
        if self.retention is None:
            raise ValueError(
                "retention is off — set "
                "ServeConfig(stream_retention_window=W)"
            )
        cut = self.retention.cutoff_for(t_commit)
        if cut is None:
            return {"edges_expired": 0, "nodes": 0,
                    "cache_invalidated": 0,
                    "graph_version": self.graph_version}
        if self.config.fenced_commits:
            with self._seq:
                with self._fence:
                    while self._inflight_flushes:
                        self._fence.wait()
                    self._cancel_prefetch()
                    exp = stream.expire_edges(cut)
                    self.retention.mark_expired(cut)
                    invalidated = 0
                    if exp["edges_expired"]:
                        self.graph_version += 1
                        if self._programs is not None:
                            self._programs.rebind(
                                graph=self._sampler.fused_graph_arrays()
                            )
                        hops = self.config.stream_invalidate_hops
                        if hops is None:
                            hops = max(len(self._sampler.sizes) - 1, 0)
                        affected = stream.affected_seeds(exp["sources"],
                                                         hops)
                        invalidated = self.cache.invalidate_nodes(
                            int(x) for x in affected
                        )
                        self.stats.edges_expired += exp["edges_expired"]
                        self.stats.delta_cache_invalidated += invalidated
        else:
            # zero-stall retention (round 24): stage the masked lane
            # writes off-fence, flip + rebind under _seq only, raise the
            # expired closure's cache floors post-flip
            with self._commit_lock:
                exp = stream.expire_edges(cut, defer_publish=True)
                self.retention.mark_expired(cut)
                invalidated = 0
                if exp["edges_expired"]:
                    new_version = self.graph_version + 1
                    hops = self.config.stream_invalidate_hops
                    if hops is None:
                        hops = max(len(self._sampler.sizes) - 1, 0)
                    affected = stream.affected_seeds(exp["sources"], hops)
                    with self._seq:
                        t_stall0 = self._clock()
                        stream.publish()
                        self.graph_version = new_version
                        if self._programs is not None:
                            self._programs.rebind(
                                graph=self._sampler.fused_graph_arrays()
                            )
                        self._cancel_prefetch()
                        stall_us = (self._clock() - t_stall0) * 1e6
                    invalidated = self.cache.raise_floor(
                        (int(x) for x in affected), new_version
                    )
                    with self._lock:
                        self.stats.edges_expired += exp["edges_expired"]
                        self.stats.delta_cache_invalidated += invalidated
                        self.stats.commit_stall.record_ms(stall_us)
        if exp["edges_expired"]:
            self.journal.emit("retention_expire", -1, self.graph_version,
                              exp["edges_expired"], exp["nodes"])
        exp["cache_invalidated"] = invalidated
        exp["graph_version"] = self.graph_version
        exp["retention_cutoff"] = cut
        return exp

    def compact_graph(self, max_moves=None) -> Dict[str, object]:
        """One background compaction pass, LSM-style: PLAN off-fence
        (reads under the stream lock only — live traffic keeps flowing),
        then flip under the `update_params` fence like an r16 migration
        (`plan_compaction` stamped the plan with version/node_version, so
        `apply_compaction` skips anything a racing commit moved first).
        Strictly observe-only on served bits: row reclaims and base-
        indirection moves never change a draw, so there is NO version
        bump and NO cache invalidation — pinned (logits + dispatch logs
        identical with compaction racing an in-flight flush) in
        tests/test_lifecycle.py. Returns the apply summary."""
        stream = getattr(self._sampler, "stream", None)
        if stream is None:
            raise ValueError(
                "compaction needs a stream-bound sampler"
            )
        if max_moves is None:
            max_moves = self.config.stream_compact_max_moves
        plan = stream.plan_compaction(max_moves=max_moves)
        self.journal.emit("compact_begin", -1, self.graph_version,
                          len(plan["retired"]) + len(plan["trims"]),
                          len(plan["moves"]))
        if self.config.fenced_commits:
            with self._seq:
                with self._fence:
                    while self._inflight_flushes:
                        self._fence.wait()
                    # staged prefetch intent survives a compaction (bytes
                    # and closures are untouched) — no _cancel_prefetch
                    summary = stream.apply_compaction(plan)
                    self.stats.tiles_reclaimed += (
                        summary["tiles_reclaimed"]
                    )
                    self.stats.compactions += 1
        else:
            # zero-stall (round 24): stage the relocated rows off-fence,
            # flip under _seq. Compaction is observe-only on bits (no
            # version bump), so there is nothing to invalidate and no
            # rebind of contents beyond the array refs themselves.
            with self._commit_lock:
                summary = stream.apply_compaction(plan,
                                                  defer_publish=True)
                with self._seq:
                    t_stall0 = self._clock()
                    stream.publish()
                    if self._programs is not None:
                        self._programs.rebind(
                            graph=self._sampler.fused_graph_arrays()
                        )
                    stall_us = (self._clock() - t_stall0) * 1e6
                with self._lock:
                    self.stats.tiles_reclaimed += (
                        summary["tiles_reclaimed"]
                    )
                    self.stats.compactions += 1
                    self.stats.commit_stall.record_ms(stall_us)
        self.journal.emit("compact_commit", -1, self.graph_version,
                          summary["tiles_reclaimed"], summary["moves"])
        summary["graph_version"] = self.graph_version
        return summary

    def provision_reserve(self, tiles=None) -> Dict[str, object]:
        """Grow the tile bank by ``tiles`` whole rows (default: the
        ``stream_provision_tiles`` knob) behind the fence, then pay the
        ONE sanctioned sealed-program rebuild
        (`inference.BucketPrograms.reprovision`) — shapes change at
        provision events only; the per-commit path still never
        recompiles. Served bits are untouched (fresh rows are free
        rows). Returns the post-grow reserve report."""
        stream = getattr(self._sampler, "stream", None)
        if stream is None:
            raise ValueError(
                "provisioning needs a stream-bound sampler"
            )
        if tiles is None:
            tiles = self.config.stream_provision_tiles
        if int(tiles) <= 0:
            raise ValueError(
                f"provision_reserve needs a positive tile count, got "
                f"{tiles} (set ServeConfig(stream_provision_tiles=...) "
                "or pass tiles=)"
            )
        with self._seq:
            with self._fence:
                while self._inflight_flushes:
                    self._fence.wait()
                self._cancel_prefetch()
                report = stream.provision_reserve(int(tiles))
                if self._programs is not None:
                    # the fence Condition wraps _lock (already held)
                    self._programs.reprovision(
                        self._sampler.fused_graph_arrays(),
                        params=self._params,
                    )
        return report

    def _compact_loop(self) -> None:
        """The background compaction daemon body: on a
        ``stream_compact_every_s`` timer, read the reserve report (no
        fence) and run `compact_graph` when `lifecycle.CompactionPolicy`
        says the reclaimable mass crossed ``stream_compact_min_reclaim``.
        A failing pass is counted in ``tier_adapt_errors``' sibling
        pattern — never fatal to serving."""
        from ..lifecycle import CompactionPolicy

        policy = CompactionPolicy(
            min_reclaimable=self.config.stream_compact_min_reclaim,
            max_moves=self.config.stream_compact_max_moves,
        )
        while self._running:
            time.sleep(self.config.stream_compact_every_s)
            if not self._running:
                return
            try:
                stream = getattr(self._sampler, "stream", None)
                if stream is None:
                    continue
                if policy.should_compact(stream.reserve_report()):
                    self.compact_graph()
            except Exception:
                self.compact_errors += 1

    def _retention_loop(self) -> None:
        """The round-23 wall-clock TTL daemon body: on a
        ``stream_retention_every_s`` timer, run one `expire_edges` pass
        — the fenced round-21 entry point, so a daemon pass IS a manual
        expiry call (fenced like update_graph; deterministic given the
        injected clock's readings, which is what the deterministic-clock
        test replays). A failing pass counts in ``retention_errors`` —
        never fatal to serving (the `_compact_loop` discipline)."""
        while self._running:
            time.sleep(self.config.stream_retention_every_s)
            if not self._running:
                return
            try:
                self._retention_pass()
            except Exception:
                self.retention_errors += 1

    def _retention_pass(self) -> Dict[str, object]:
        """One daemon pass, callable directly (tests drive it with a
        deterministic clock instead of sleeping): advance event time to
        ``stream_retention_clock()`` when a clock is configured (None =
        re-check the commit-driven retention clock's standing cutoff)
        and expire behind the fence."""
        clk = self.config.stream_retention_clock
        exp = self.expire_edges(
            t_commit=clk() if clk is not None else None
        )
        self.retention_passes += 1
        return exp

    # -- adaptive tier placement (round 14) --------------------------------

    def apply_placement(self, plan) -> Dict[str, object]:
        """Move rows between disk <-> DRAM <-> HBM behind the SAME fence
        as `update_params`: block new assembles (the sequencing lock),
        drain every in-flight flush, apply the batch, bump
        ``placement_version``, and invalidate the moved rows' embedding-
        cache entries. No flush ever straddles a placement batch, so a
        frozen placement replays bit-identically — and because every
        row's bytes live on the disk backing permanently, the move
        itself changes no gathered byte (the bit-parity pin in
        tests/test_tiers.py). Returns the `TierStore.apply` summary."""
        feat = self._tier_feature
        if feat is None:
            raise ValueError(
                "no adaptive tier store under this engine's feature "
                "(build it with Feature(disk_path=..., adaptive_tiers=True))"
            )
        with self._seq:
            with self._fence:
                while self._inflight_flushes:
                    self._fence.wait()
                # TierStore.apply cancels the staged rows itself, but the
                # ENGINE's submit-walk memo must clear with them: after a
                # placement batch "already walked" no longer implies
                # "already staged", and a stale memo would quietly skip
                # re-staging at the next assemble (hit-rate loss, not a
                # bit error)
                self._cancel_prefetch()
                summary = feat.tier_store.apply(plan)
                self.placement_version += 1
                self.stats.tier_promoted += summary["promoted_rows"]
                self.stats.tier_demoted += summary["demoted_rows"]
                self.stats.placement_batches += 1
                moved = summary["moved_stored"]
                if moved.size:
                    nodes = feat.node_ids_of_stored(moved)
                    summary["cache_invalidated"] = self.cache.invalidate_keys(
                        int(x) for x in nodes[nodes >= 0]
                    )
                else:
                    summary["cache_invalidated"] = 0
        return summary

    def adapt_tiers(self, max_moves: Optional[int] = None) -> Dict[str, object]:
        """ONE sketch-driven promote/demote pass: read the live frequency
        sketch (`WorkloadMonitor.promotion_candidates`, err-corrected),
        map the hot head into stored-row space, price current residents
        against the Count-Min estimate, plan a bounded batch
        (`tiers.plan_adaptive` — hysteresis keeps near-ties from
        ping-ponging), and apply it behind the placement fence. Safe to
        call any time; a no-move plan skips the fence entirely. This is
        the consumer ROADMAP item 2 names — `start()` runs it on a timer
        when ``tier_adapt_every_s`` > 0, tests call it synchronously."""
        from ..tiers import plan_adaptive

        feat = self._tier_feature
        if feat is None:
            raise ValueError(
                "no adaptive tier store under this engine's feature"
            )
        if self.workload is None:
            raise ValueError(
                "tier adaptation reads the frequency sketch — pass "
                "ServeConfig(workload=WorkloadConfig(...))"
            )
        wl = self.workload
        store = feat.tier_store
        empty = {"moves": 0, "promoted_rows": 0, "demoted_rows": 0,
                 "version": store.placement_version,
                 "counts": store.placement.counts()}
        if wl.row_sketch is not None:
            # preferred input: the ROW sketch measures what the tiers
            # actually serve (seeds + sampled neighbors), already keyed
            # by stored row
            cand = wl.row_promotion_candidates(
                min_weight=self.config.tier_promote_min
            )
            if not cand:
                return empty
            stored = np.asarray([k for k, _ in cand], np.int64)
            weights = np.asarray([w for _, w in cand], np.float64)
            ok = (stored >= 0) & (stored < store.n_rows)
            rcms = wl.row_cms

            def resident_weight(stored_ids: np.ndarray) -> np.ndarray:
                return np.asarray(
                    [rcms.estimate(int(s)) for s in stored_ids], np.float64
                )
        else:
            # fallback: the seed sketch (what clients ASK), mapped into
            # stored-row space — blind to neighbor gathers, so prefer
            # row_topk when tier adaptation is the point
            cand = wl.promotion_candidates(
                min_weight=self.config.tier_promote_min
            )
            if not cand:
                return empty
            nodes = np.asarray([k for k, _ in cand], np.int64)
            weights = np.asarray([w for _, w in cand], np.float64)
            stored = feat.stored_rows_of(nodes)
            ok = stored >= 0  # unowned/out-of-range keys (dist shards)
            cms = wl.cms

            def resident_weight(stored_ids: np.ndarray) -> np.ndarray:
                res_nodes = feat.node_ids_of_stored(stored_ids)
                return np.asarray(
                    [cms.estimate(int(x)) if x >= 0 else 0.0
                     for x in res_nodes],
                    np.float64,
                )

        plan = plan_adaptive(
            store.placement, stored[ok], weights[ok],
            resident_weight=resident_weight,
            max_moves=max_moves or self.config.tier_promote_batch,
            min_weight=self.config.tier_promote_min,
            hysteresis=self.config.tier_hysteresis,
        )
        if not len(plan):
            return {"moves": 0, "promoted_rows": 0, "demoted_rows": 0,
                    "version": store.placement_version,
                    "counts": store.placement.counts()}
        return self.apply_placement(plan)

    def _tier_loop(self) -> None:
        from ..tiers import tier_daemon_loop

        tier_daemon_loop(self)

    # -- background flushers ----------------------------------------------

    def start(self) -> "ServeEngine":
        """Start ``max_in_flight`` poller threads, each applying the flush
        policy on a timer. With a window > 1 the pollers (plus inline
        submit flushes) are what actually overlap assemble with device
        execution for single-threaded clients."""
        if self._running:
            return self
        self._running = True
        self._threads = [
            threading.Thread(
                target=self._poll_loop,
                name=f"quiver-serve-flusher-{i}",
                daemon=True,
            )
            for i in range(self.config.max_in_flight)
        ]
        if (
            self.config.tier_adapt_every_s > 0
            and self._tier_feature is not None
            and self.workload is not None
        ):
            # the round-14 promote/demote consumer: reads the sketch on a
            # timer, applies bounded fenced batches (see adapt_tiers)
            self._threads.append(
                threading.Thread(
                    target=self._tier_loop,
                    name="quiver-serve-tiers",
                    daemon=True,
                )
            )
        if (
            self.config.stream_compact_every_s > 0
            and getattr(self._sampler, "stream", None) is not None
        ):
            # the round-21 background compactor: plans off-fence, flips
            # under the fence, observe-only on bits (see compact_graph)
            self._threads.append(
                threading.Thread(
                    target=self._compact_loop,
                    name="quiver-serve-compactor",
                    daemon=True,
                )
            )
        if (
            self.config.stream_retention_every_s > 0
            and self.retention is not None
            and getattr(self._sampler, "stream", None) is not None
            and getattr(self._sampler.stream, "temporal", False)
        ):
            # the round-23 wall-clock TTL daemon: keeps a QUIET temporal
            # stream's sliding window expiring between commits (see
            # _retention_loop); fenced like update_graph, off by default
            self._threads.append(
                threading.Thread(
                    target=self._retention_loop,
                    name="quiver-serve-retention",
                    daemon=True,
                )
            )
        for t in self._threads:
            t.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the background threads and retire queued work, BOUNDED by
        ``config.drain_deadline_s``: a poller or owner thread that died
        mid-flush must not hang the caller forever. Work not retired by
        the deadline resolves with `DrainTimeout` (waiters unblock, never
        hang) and is counted in ``stats.undrained`` — visible in the
        stats snapshot, never silently dropped."""
        self._running = False
        # the WHOLE stop — poller joins included — shares one deadline: a
        # poller wedged mid-flush (owner blocked in predict) would defeat
        # the bound if joined without a timeout
        deadline = self._clock() + self.config.drain_deadline_s
        for t in self._threads:
            t.join(timeout=max(deadline - self._clock(), 0.05))
        self._threads = []
        if drain:
            while self._drainable() and self._clock() < deadline:
                try:
                    self.flush()
                except Exception:
                    pass  # the failing flush resolved its own waiters
        # even without drain, leave no flush mid-air: callers expect stats
        # and handles quiescent after stop()
        with self._fence:
            while self._inflight_flushes and self._clock() < deadline:
                self._fence.wait(timeout=0.05)
        # staged prefetch rows outlive their flushes at stop: cancel so
        # the pool's futures are observed (no GC log spam) and the waste
        # ledger closes — pinned leak-free in tests/test_prefetch.py
        self._cancel_prefetch()
        abandon_undrained(self, drained=drain)

    def _poll_loop(self) -> None:
        while self._running:
            try:
                self.pump()
            except Exception:
                # the failing flush already resolved its waiters with the
                # error; keep serving subsequent requests
                pass
            time.sleep(self.config.flush_poll_ms / 1e3)

    def __enter__(self) -> "ServeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

"""Online serving engine: dynamic micro-batching, request coalescing, and a
params-versioned embedding cache.

`inference.sampled_eval` is an OFFLINE loop: it owns its batch composition
and pays one sample + gather + forward per 1024 seeds. Online traffic
inverts every assumption — requests arrive one at a time, skewed toward hot
nodes, and each caller wants ONE row of logits at low latency. Paying a
full dispatch per request would burn the whole device budget on padding;
this engine turns the request stream back into efficient fixed-shape device
work with three levers, applied in order of cheapness:

1. **Embedding cache** (:class:`quiver_tpu.serve.cache.EmbeddingCache`):
   repeat requests for a node already computed under the CURRENT
   ``params_version`` are answered from host memory — no device work at
   all. `update_params` bumps the version and invalidates, so a served
   result may be cache-aged but never crosses a weight update.
2. **Cross-request coalescing**: within a flush window, identical seed ids
   collapse to ONE slot — 50 concurrent callers asking for the same hot
   node cost one sample/gather/forward and share the result. Requests
   arriving while that node is in flight attach to the in-flight slot.
3. **Dynamic micro-batching**: cache-missing unique seeds queue until
   ``max_batch`` are waiting or the oldest has aged ``max_delay_ms``, then
   flush as one batch padded to a fixed BUCKET size (powers of two up to
   ``max_batch`` by default). Fixed buckets mean one compiled program per
   bucket serves all traffic — no per-request recompiles, ever.

The device path is `inference.batch_logits` — the exact `sampled_eval`
inner step (same sampler stream, same pad convention, same lookup, same
cached jitted apply). That shared path is what makes served logits
BIT-IDENTICAL to offline eval on the same (sampler state, batch) pair; the
parity test replays the engine's dispatch log through a fresh sampler and
compares exactly (tests/test_serve.py).

Threading model: any number of client threads `submit`; one flush runs at a
time (``_dispatch_lock`` serializes device work and keeps the sampler's
key stream, ``_call`` indexed, deterministic in dispatch order). The engine
is fully functional without its background thread — `submit` flushes
inline when a batch fills, and `pump`/`flush` drive the delay policy
manually, which is how the deterministic tests run it with an injected
clock. `start()` adds a poller thread for real deployments.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..inference import _cached_apply, batch_logits, pad_seed_batch
from ..trace import HitRateCounter, LatencyHistogram
from .cache import EmbeddingCache


def default_buckets(max_batch: int) -> Tuple[int, ...]:
    """Powers of two up to ``max_batch`` (inclusive, appended if it is not
    itself a power of two): the bucket ladder that bounds padding waste at
    2x while keeping the compiled-program count at ``log2(max_batch)``."""
    if max_batch < 1:
        raise ValueError("max_batch must be >= 1")
    out: List[int] = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(out)


@dataclass
class ServeConfig:
    """Engine knobs (see docs/api.md "Online serving").

    max_batch      : flush as soon as this many unique cache-missing seeds
                     are pending (also the largest bucket).
    max_delay_ms   : flush a non-empty queue once its OLDEST request has
                     waited this long — the latency/throughput trade knob.
    buckets        : fixed batch shapes; a flush pads up to the smallest
                     bucket >= its unique-seed count. Default: powers of
                     two up to ``max_batch``. One compiled program per
                     bucket actually used.
    cache_entries  : embedding-cache capacity in rows (0 disables caching).
    clock          : injectable monotonic clock (seconds) — latency metrics
                     and the delay policy read ONLY this, so tests drive
                     flush timing deterministically with a fake clock.
    flush_poll_ms  : background flusher poll period (`start()` mode only).
    record_dispatches : keep a log of (padded_batch, n_valid) per dispatch
                     for parity replay/debugging (off by default: it grows
                     with traffic).
    """

    max_batch: int = 64
    max_delay_ms: float = 2.0
    buckets: Optional[Sequence[int]] = None
    cache_entries: int = 100_000
    clock: Callable[[], float] = time.monotonic
    flush_poll_ms: float = 0.2
    record_dispatches: bool = False

    def resolved_buckets(self) -> Tuple[int, ...]:
        if self.buckets is None:
            return default_buckets(self.max_batch)
        bs = tuple(sorted(int(b) for b in self.buckets))
        if not bs or bs[0] < 1:
            raise ValueError("buckets must be positive")
        if bs[-1] < self.max_batch:
            raise ValueError(
                f"largest bucket {bs[-1]} < max_batch {self.max_batch}: "
                "a full flush would not fit any bucket"
            )
        return bs


class _Slot:
    """One unique (node_id, params_version) computation; every coalesced
    request for it holds a reference and blocks on ``event``."""

    __slots__ = ("node_id", "version", "event", "value", "error", "enqueue_t", "waiters")

    def __init__(self, node_id: int, version: int, enqueue_t: float):
        self.node_id = node_id
        self.version = version
        self.event = threading.Event()
        self.value: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.enqueue_t = enqueue_t
        self.waiters: List[float] = []  # submit timestamps, for latency

    def resolve(self, value: Optional[np.ndarray], error=None) -> None:
        self.value = value
        self.error = error
        self.event.set()


class ServeResult:
    """Handle returned by :meth:`ServeEngine.submit`."""

    __slots__ = ("_slot", "_value")

    def __init__(self, slot: Optional[_Slot] = None, value: Optional[np.ndarray] = None):
        self._slot = slot
        self._value = value

    def done(self) -> bool:
        return self._slot is None or self._slot.event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Logits row for the requested node (blocks until its flush
        lands; raises the flush's exception if the dispatch failed).

        The row is READ-ONLY — it is shared with the embedding cache and
        every coalesced co-waiter. Copy before mutating."""
        if self._slot is None:
            return self._value
        if not self._slot.event.wait(timeout):
            raise TimeoutError("serve request not resolved in time")
        if self._slot.error is not None:
            raise self._slot.error
        return self._slot.value


@dataclass
class ServeStats:
    """Engine counters. ``requests`` counts every submit; ``coalesced``
    the subset answered by attaching to an existing pending/in-flight slot;
    the cache's own hit/miss/eviction counters live in ``cache``.
    ``dispatches`` is the number of device batches actually launched —
    the acceptance metric "dispatch count < N" reads this."""

    requests: int = 0
    coalesced: int = 0
    dispatches: int = 0
    dispatched_seeds: int = 0   # unique seeds sent to the device
    padded_seeds: int = 0       # bucket slack rows computed and discarded
    dispatch_buckets: Dict[int, int] = field(default_factory=dict)
    cache: HitRateCounter = field(default_factory=HitRateCounter)
    latency: LatencyHistogram = field(default_factory=LatencyHistogram)

    def snapshot(self) -> Dict[str, object]:
        return {
            "requests": self.requests,
            "coalesced": self.coalesced,
            "dispatches": self.dispatches,
            "dispatched_seeds": self.dispatched_seeds,
            "padded_seeds": self.padded_seeds,
            "dispatch_buckets": dict(self.dispatch_buckets),
            "cache": self.cache.snapshot(),
            "latency": self.latency.snapshot(),
        }


class ServeEngine:
    """See the module docstring for the design; docs/api.md for the
    contract. Typical use::

        engine = ServeEngine(model, params, sampler, feature,
                             ServeConfig(max_batch=32, max_delay_ms=2.0))
        with engine:                      # starts the background flusher
            logits = engine.predict([node_id])[0]

    or fully synchronous (no thread)::

        h = engine.submit(node_id)
        engine.flush()
        logits = h.result()
    """

    def __init__(self, model, params, sampler, feature,
                 config: Optional[ServeConfig] = None):
        self.config = config or ServeConfig()
        self._buckets = self.config.resolved_buckets()
        self._apply = _cached_apply(model)
        self._params = params
        self._sampler = sampler
        self._feature = feature
        self._clock = self.config.clock
        self.stats = ServeStats()
        self.cache = EmbeddingCache(self.config.cache_entries,
                                    counters=self.stats.cache)
        self.params_version = 0
        self.dispatch_log: List[Tuple[np.ndarray, int]] = []
        # queue state: _pending holds slots not yet flushed (insertion order
        # = FIFO), _inflight slots snapshot-ed by a running flush
        self._pending: "Dict[int, _Slot]" = {}
        self._inflight: Dict[int, _Slot] = {}
        self._lock = threading.Lock()          # queue + cache-version state
        self._dispatch_lock = threading.Lock() # serializes device work
        self._seed_bufs: Dict[Tuple[int, object], np.ndarray] = {}
        self._thread: Optional[threading.Thread] = None
        self._running = False

    # -- request path -----------------------------------------------------

    def submit(self, node_id: int) -> ServeResult:
        """Enqueue one node-prediction request; returns a handle. Fills of
        ``max_batch`` flush inline on the submitting thread."""
        key = int(node_id)
        now = self._clock()
        need_flush = False
        with self._lock:
            self.stats.requests += 1
            cached = self.cache.get(key, self.params_version)
            if cached is not None:
                self.stats.latency.record_ms((self._clock() - now) * 1e3)
                return ServeResult(value=cached)
            slot = self._pending.get(key) or self._inflight.get(key)
            if slot is not None and slot.version == self.params_version:
                self.stats.coalesced += 1
            else:
                slot = _Slot(key, self.params_version, now)
                self._pending[key] = slot
            slot.waiters.append(now)
            if len(self._pending) >= self.config.max_batch:
                need_flush = True
        if need_flush:
            self.flush()
        return ServeResult(slot=slot)

    def predict(self, node_ids, timeout: Optional[float] = None) -> np.ndarray:
        """Blocking convenience: submit every id, make sure they flush
        (inline when no background thread is running), return ``[len(ids),
        C]`` logits in request order."""
        handles = [self.submit(i) for i in np.asarray(node_ids).reshape(-1)]
        if not handles:  # empty batch is a valid no-op (np.stack would raise)
            return np.zeros((0, 0), np.float32)
        if not self._running:
            while any(not h.done() for h in handles) and self._drainable():
                self.flush()
        return np.stack([h.result(timeout) for h in handles])

    # -- flush policy -----------------------------------------------------

    def should_flush(self) -> bool:
        with self._lock:
            if not self._pending:
                return False
            if len(self._pending) >= self.config.max_batch:
                return True
            oldest = next(iter(self._pending.values())).enqueue_t
            return (self._clock() - oldest) * 1e3 >= self.config.max_delay_ms

    def pump(self) -> int:
        """Apply the flush policy once: flush iff ``max_batch`` or
        ``max_delay_ms`` demands it. Returns seeds dispatched (0 if the
        policy held). This is the deterministic-test / external-event-loop
        surface; the background thread just calls it on a poll timer."""
        return self.flush() if self.should_flush() else 0

    def flush(self) -> int:
        """Dispatch up to ``max_batch`` pending unique seeds as one bucket-
        padded device batch NOW (policy bypassed). Returns the number of
        unique seeds dispatched."""
        with self._dispatch_lock:
            with self._lock:
                if not self._pending:
                    return 0
                keys = list(self._pending)[: self.config.max_batch]
                slots = [self._pending.pop(k) for k in keys]
                self._inflight.update(zip(keys, slots))
                # params snapshot only: version checks below deliberately
                # re-read self.params_version so a concurrent update_params
                # suppresses caching of the now-stale rows
                params = self._params
            try:
                seeds = np.asarray(keys, dtype=np.int64)
                bucket = self._bucket_for(len(seeds))
                buf = self._seed_bufs.get((bucket, seeds.dtype.str))
                padded = pad_seed_batch(seeds, bucket, out=buf)
                self._seed_bufs[(bucket, seeds.dtype.str)] = padded
                if self.config.record_dispatches:
                    self.dispatch_log.append((padded.copy(), len(seeds)))
                logits = np.asarray(batch_logits(
                    self._apply, params, self._sampler, self._feature, padded
                ))
                # rows of this array are handed to every waiter AND the
                # cache; read-only makes an in-place mutation by one caller
                # a loud ValueError instead of silently corrupting every
                # later cache hit for the node
                if logits.flags.writeable:
                    logits.setflags(write=False)
                err = None
            except BaseException as exc:  # resolve waiters, then re-raise
                logits, err = None, exc
            now = self._clock()
            with self._lock:
                for i, (k, slot) in enumerate(zip(keys, slots)):
                    self._inflight.pop(k, None)
                    if err is None:
                        row = logits[i]
                        if slot.version == self.params_version:
                            self.cache.put(k, slot.version, row)
                        slot.resolve(row)
                    else:
                        slot.resolve(None, error=err)
                    for t0 in slot.waiters:
                        self.stats.latency.record_ms((now - t0) * 1e3)
                if err is None:
                    self.stats.dispatches += 1
                    self.stats.dispatched_seeds += len(seeds)
                    self.stats.padded_seeds += bucket - len(seeds)
                    self.stats.dispatch_buckets[bucket] = (
                        self.stats.dispatch_buckets.get(bucket, 0) + 1
                    )
            if err is not None:
                raise err
            return len(seeds)

    def _bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if b >= n:
                return b
        return self._buckets[-1]

    def _drainable(self) -> bool:
        with self._lock:
            return bool(self._pending)

    def reset_stats(self) -> None:
        """Zero every counter/histogram AND re-point the embedding cache's
        counter at the fresh `ServeStats` (the two must move together — a
        bare ``stats.__init__()`` would leave the cache counting into the
        detached old object). Benches call this after their warm-up pass;
        cache CONTENTS are untouched (use `cache.invalidate()` for that)."""
        with self._lock:
            self.stats = ServeStats()
            self.cache.counters = self.stats.cache

    # -- weight updates ---------------------------------------------------

    def update_params(self, params) -> None:
        """Install new weights: bump ``params_version`` and invalidate the
        embedding cache. Pending (not yet dispatched) slots are re-stamped
        to the new version — their flush will compute under the new weights.
        In-flight flushes of the OLD version still resolve their waiters
        (those requests were accepted under the old weights) but their
        results are never cached under the new version."""
        with self._lock:
            self._params = params
            self.params_version += 1
            self.cache.invalidate()
            for slot in self._pending.values():
                slot.version = self.params_version

    # -- background flusher -----------------------------------------------

    def start(self) -> "ServeEngine":
        if self._running:
            return self
        self._running = True
        self._thread = threading.Thread(
            target=self._poll_loop, name="quiver-serve-flusher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if drain:
            while self._drainable():
                self.flush()

    def _poll_loop(self) -> None:
        while self._running:
            try:
                self.pump()
            except Exception:
                # the failing flush already resolved its waiters with the
                # error; keep serving subsequent requests
                pass
            time.sleep(self.config.flush_poll_ms / 1e3)

    def __enter__(self) -> "ServeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

"""Seeded synthetic request traces for the serving benchmarks.

Online node-prediction traffic is skewed: a handful of hub nodes (popular
items, celebrity accounts) absorb most requests. The standard model is a
Zipfian popularity law — request probability of the rank-``r`` node
proportional to ``1/r**alpha`` — with ``alpha`` around 0.6-1.1 for web/
recommendation workloads. These generators are fully seeded so every probe
and test replays bit-identically; the rank->node mapping is a seeded
permutation so "hot" nodes are scattered across the id space rather than
being ids 0..k (which would alias with the degree-ordered hot feature
prefix and flatter the cache).
"""

from __future__ import annotations

from typing import Iterator, NamedTuple, Tuple

import numpy as np


def zipfian_trace(
    n_nodes: int, n_requests: int, alpha: float = 0.99, seed: int = 0
) -> np.ndarray:
    """``[n_requests]`` int64 node ids drawn Zipf(``alpha``) over
    ``n_nodes`` ranks (``alpha=0`` -> uniform). Deterministic per
    ``(n_nodes, n_requests, alpha, seed)``."""
    if n_nodes <= 0 or n_requests < 0:
        raise ValueError("need n_nodes > 0 and n_requests >= 0")
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_nodes + 1, dtype=np.float64)
    p = ranks ** (-float(alpha))
    p /= p.sum()
    drawn_ranks = rng.choice(n_nodes, size=n_requests, p=p)
    node_of_rank = rng.permutation(n_nodes).astype(np.int64)
    return node_of_rank[drawn_ranks]


def poisson_arrivals(
    n_requests: int, qps: float, seed: int = 0
) -> np.ndarray:
    """``[n_requests]`` float64 cumulative arrival times (seconds) of a
    Poisson process at rate ``qps`` — the open-loop replay schedule for
    latency-under-load probes."""
    if qps <= 0:
        raise ValueError("qps must be > 0")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / qps, size=n_requests)
    return np.cumsum(gaps)


class DeltaTrace(NamedTuple):
    """A request trace with seeded EDGE-ARRIVAL events woven in (round
    17): ``requests`` is a plain `zipfian_trace` (byte-identical to the
    frozen-graph trace at the same arguments — the empty-delta parity
    legs ride that); arrival event ``i`` commits edges
    ``(edge_src[i], edge_dst[i])`` immediately BEFORE request index
    ``edge_pos[i]`` is submitted. Everything is derived from the seed, so
    probes and tests drive graph churn deterministically."""

    requests: np.ndarray   # [n_requests] int64 node ids
    edge_pos: np.ndarray   # [n_events] int64 request index per event
    edge_src: np.ndarray   # [n_events, edges_per_event] int64
    edge_dst: np.ndarray   # [n_events, edges_per_event] int64

    @property
    def n_events(self) -> int:
        return int(self.edge_pos.shape[0])

    def events(self) -> Iterator[Tuple[str, object, object]]:
        """The interleaved schedule: yields ``("edges", src_row,
        dst_row)`` then ``("request", index, node)`` in commit order —
        the one iteration a driver loop needs."""
        e = 0
        for i, node in enumerate(self.requests):
            while e < self.n_events and int(self.edge_pos[e]) == i:
                yield ("edges", self.edge_src[e], self.edge_dst[e])
                e += 1
            yield ("request", i, int(node))

    def request_bursts(self):
        """Array-at-a-time replay schedule (round 20): yields ``("edges",
        src_row, dst_row)`` events and ``("requests", start_index,
        node_array)`` BURSTS — each burst the maximal run of requests
        between consecutive edge events, the natural `submit_many` unit.
        Same commit order as `events` (an ``("edges", ...)`` that lands
        before request ``i`` is yielded before the burst containing
        ``i``), so a batched driver observes the identical schedule."""
        for kind, start, end, e in _burst_spans(self.requests.shape[0],
                                                self.edge_pos):
            if kind == "edges":
                yield ("edges", self.edge_src[e], self.edge_dst[e])
            else:
                yield ("requests", start, self.requests[start:end])


def _burst_spans(n_requests: int, edge_pos: np.ndarray):
    """The shared burst walk behind both ``request_bursts`` spellings:
    yields ``("edges", -1, -1, event_index)`` and ``("requests", start,
    end, -1)`` spans in commit order (an event at position ``p`` fires
    before the burst starting at ``p``)."""
    e = 0
    n_events = int(edge_pos.shape[0])
    i = 0
    while i < n_requests:
        while e < n_events and int(edge_pos[e]) == i:
            yield ("edges", -1, -1, e)
            e += 1
        end = int(edge_pos[e]) if e < n_events else n_requests
        end = min(max(end, i + 1), n_requests)
        yield ("requests", i, end, -1)
        i = end
    # like `events`: edge positions at/after n_requests never fire


def delta_interleaved_trace(
    n_nodes: int,
    n_requests: int,
    alpha: float = 0.99,
    seed: int = 0,
    edge_every: int = 32,
    edges_per_event: int = 4,
) -> DeltaTrace:
    """Weave seeded edge arrivals into a `zipfian_trace`: one event every
    ``edge_every`` requests, each carrying ``edges_per_event`` new edges.
    Sources are drawn from the PREFIX of the request trace served so far
    (arrivals correlate with live traffic — new edges land on nodes the
    cache and sketches already consider hot, the feed/fraud shape);
    destinations are uniform, self-loops nudged off. The request stream
    is byte-identical to ``zipfian_trace(n_nodes, n_requests, alpha,
    seed)`` — delta events ride a separate seeded generator, so the
    frozen-graph and streaming runs compare like for like."""
    if edge_every <= 0 or edges_per_event <= 0:
        raise ValueError("edge_every and edges_per_event must be > 0")
    requests = zipfian_trace(n_nodes, n_requests, alpha=alpha, seed=seed)
    rng = np.random.default_rng([int(seed), 0x5EED])
    pos = np.arange(edge_every, n_requests, edge_every, dtype=np.int64)
    k = pos.shape[0]
    src = np.zeros((k, edges_per_event), np.int64)
    dst = np.zeros((k, edges_per_event), np.int64)
    for i, p in enumerate(pos):
        picks = rng.integers(0, int(p), edges_per_event)
        src[i] = requests[picks]
        dst[i] = rng.integers(0, n_nodes, edges_per_event)
    loops = src == dst
    dst[loops] = (dst[loops] + 1) % n_nodes
    return DeltaTrace(requests=requests, edge_pos=pos, edge_src=src,
                      edge_dst=dst)


class TemporalTrace(NamedTuple):
    """Arrival-stamped temporal queries interleaved with timestamped edge
    appends (round 19): request ``i`` asks for ``requests[i]`` AS OF
    ``t_query[i]`` (its own arrival time on a seeded Poisson clock — the
    feed-ranking shape: you rank against the graph as it exists when you
    ask); arrival event ``j`` commits edges ``(edge_src[j], edge_dst[j])``
    with per-edge timestamps ``edge_ts[j]`` immediately BEFORE request
    index ``edge_pos[j]``, and every committed timestamp precedes the
    next query's t — so the "edge arrives, next ``ts <= t`` query sees
    it" contract is exercised by construction. Byte-deterministic under
    a fixed seed."""

    requests: np.ndarray   # [n_requests] int64 node ids
    t_query: np.ndarray    # [n_requests] float64 query times (monotone)
    edge_pos: np.ndarray   # [n_events] int64 request index per event
    edge_src: np.ndarray   # [n_events, edges_per_event] int64
    edge_dst: np.ndarray   # [n_events, edges_per_event] int64
    edge_ts: np.ndarray    # [n_events, edges_per_event] float64

    @property
    def n_events(self) -> int:
        return int(self.edge_pos.shape[0])

    def events(self):
        """Yields ``("edges", src_row, dst_row, ts_row)`` then
        ``("request", index, node, t)`` in commit order."""
        e = 0
        for i, node in enumerate(self.requests):
            while e < self.n_events and int(self.edge_pos[e]) == i:
                yield ("edges", self.edge_src[e], self.edge_dst[e],
                       self.edge_ts[e])
                e += 1
            yield ("request", i, int(node), float(self.t_query[i]))

    def request_bursts(self):
        """Array-at-a-time schedule (round 20, see
        `DeltaTrace.request_bursts`): yields ``("edges", src_row,
        dst_row, ts_row)`` events and ``("requests", start_index,
        node_array, t_array)`` bursts — node ids with their aligned query
        times, ready for ``submit_many(ids, t=ts)``."""
        for kind, start, end, e in _burst_spans(self.requests.shape[0],
                                                self.edge_pos):
            if kind == "edges":
                yield ("edges", self.edge_src[e], self.edge_dst[e],
                       self.edge_ts[e])
            else:
                yield ("requests", start, self.requests[start:end],
                       self.t_query[start:end])


def temporal_trace(
    n_nodes: int,
    n_requests: int,
    alpha: float = 0.99,
    seed: int = 0,
    qps: float = 1000.0,
    t0: float = 0.0,
    edge_every: int = 32,
    edges_per_event: int = 4,
) -> TemporalTrace:
    """Seeded temporal drive traffic: a `zipfian_trace` node stream with
    `poisson_arrivals` query times starting at ``t0`` (so base-graph
    timestamps below ``t0`` are all in the past), and one edge-append
    event every ``edge_every`` requests. Event sources are drawn from the
    served PREFIX (arrivals correlate with live traffic, like
    `delta_interleaved_trace`); each appended edge's timestamp lands
    strictly between the previous and next query times, so it is
    invisible to every earlier query and visible to every later one at
    that source — the per-commit visibility assert the probe rides.
    Everything derives from ``seed``; two calls are byte-identical."""
    if edge_every <= 0 or edges_per_event <= 0:
        raise ValueError("edge_every and edges_per_event must be > 0")
    requests = zipfian_trace(n_nodes, n_requests, alpha=alpha, seed=seed)
    t_query = t0 + poisson_arrivals(n_requests, qps, seed=seed)
    rng = np.random.default_rng([int(seed), 0x7E4D])
    pos = np.arange(edge_every, n_requests, edge_every, dtype=np.int64)
    k = pos.shape[0]
    src = np.zeros((k, edges_per_event), np.int64)
    dst = np.zeros((k, edges_per_event), np.int64)
    ets = np.zeros((k, edges_per_event), np.float64)
    for i, p in enumerate(pos):
        picks = rng.integers(0, int(p), edges_per_event)
        src[i] = requests[picks]
        dst[i] = rng.integers(0, n_nodes, edges_per_event)
        # strictly between the neighboring query times: u in (0, 1) open
        lo, hi = float(t_query[p - 1]), float(t_query[p])
        u = rng.uniform(0.05, 0.95, edges_per_event)
        ets[i] = lo + u * (hi - lo)
    loops = src == dst
    dst[loops] = (dst[loops] + 1) % n_nodes
    return TemporalTrace(requests=requests, t_query=t_query, edge_pos=pos,
                         edge_src=src, edge_dst=dst, edge_ts=ets)


class LPTrace(NamedTuple):
    """A link-prediction request stream (round 19): candidate pairs
    ``(u[i], v[i])`` with ``label[i]`` 1 for a true edge of the graph and
    0 for a sampled negative, queried at ``t_query[i]``. Negatives pair
    a source from the SERVED PREFIX (a node retrieval has already
    touched — the production shape: you re-rank candidates for active
    users) with a uniform non-self destination. Byte-deterministic under
    a fixed seed."""

    u: np.ndarray        # [n_pairs] int64
    v: np.ndarray        # [n_pairs] int64
    label: np.ndarray    # [n_pairs] int8 (1 = true edge, 0 = negative)
    t_query: np.ndarray  # [n_pairs] float64


def lp_trace(
    csr_topo,
    n_pairs: int,
    alpha: float = 0.99,
    seed: int = 0,
    pos_frac: float = 0.5,
    qps: float = 1000.0,
    t0: float = 0.0,
) -> LPTrace:
    """Seeded LP traffic over a graph: ``pos_frac`` of pairs are true
    edges (source drawn Zipf-hot, destination a uniformly drawn neighbor
    of it); the rest are negatives sampled from the served prefix —
    source drawn from the pairs already emitted (the prefix; the first
    request falls back to the Zipf draw), destination uniform with
    self-loops nudged off. Degree-0 sources fall back to negatives, so
    every row is well-defined on any graph."""
    if n_pairs < 0 or not 0.0 <= pos_frac <= 1.0:
        raise ValueError("need n_pairs >= 0 and 0 <= pos_frac <= 1")
    indptr = np.asarray(csr_topo.indptr, np.int64)
    indices = np.asarray(csr_topo.indices, np.int64)
    n_nodes = indptr.shape[0] - 1
    hot = zipfian_trace(n_nodes, n_pairs, alpha=alpha, seed=seed)
    t_query = t0 + poisson_arrivals(n_pairs, qps, seed=seed)
    rng = np.random.default_rng([int(seed), 0x1B9A])
    u = np.zeros(n_pairs, np.int64)
    v = np.zeros(n_pairs, np.int64)
    label = np.zeros(n_pairs, np.int8)
    for i in range(n_pairs):
        want_pos = rng.uniform() < pos_frac
        src = int(hot[i])
        deg = int(indptr[src + 1] - indptr[src])
        if want_pos and deg > 0:
            u[i] = src
            v[i] = int(indices[indptr[src] + rng.integers(0, deg)])
            label[i] = 1
        else:
            served = u[:i]
            u[i] = int(served[rng.integers(0, i)]) if i else src
            d = int(rng.integers(0, n_nodes))
            if d == u[i]:
                d = (d + 1) % n_nodes
            v[i] = d
            label[i] = 0
    return LPTrace(u=u, v=v, label=label, t_query=t_query)


def trace_skew_stats(trace: np.ndarray, top_frac: float = 0.01) -> dict:
    """Observed skew of a trace: unique fraction and the request share of
    the hottest ``top_frac`` of distinct nodes (the number a cache planner
    actually wants)."""
    trace = np.asarray(trace)
    if trace.size == 0:
        return {"unique_frac": 0.0, "top_share": 0.0, "distinct": 0}
    _, counts = np.unique(trace, return_counts=True)
    counts = np.sort(counts)[::-1]
    k = max(1, int(np.ceil(top_frac * counts.size)))
    return {
        "unique_frac": counts.size / trace.size,
        "top_share": float(counts[:k].sum() / trace.size),
        "distinct": int(counts.size),
    }

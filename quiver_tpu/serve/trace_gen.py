"""Seeded synthetic request traces for the serving benchmarks.

Online node-prediction traffic is skewed: a handful of hub nodes (popular
items, celebrity accounts) absorb most requests. The standard model is a
Zipfian popularity law — request probability of the rank-``r`` node
proportional to ``1/r**alpha`` — with ``alpha`` around 0.6-1.1 for web/
recommendation workloads. These generators are fully seeded so every probe
and test replays bit-identically; the rank->node mapping is a seeded
permutation so "hot" nodes are scattered across the id space rather than
being ids 0..k (which would alias with the degree-ordered hot feature
prefix and flatter the cache).
"""

from __future__ import annotations

from typing import Iterator, NamedTuple, Tuple

import numpy as np


def zipfian_trace(
    n_nodes: int, n_requests: int, alpha: float = 0.99, seed: int = 0
) -> np.ndarray:
    """``[n_requests]`` int64 node ids drawn Zipf(``alpha``) over
    ``n_nodes`` ranks (``alpha=0`` -> uniform). Deterministic per
    ``(n_nodes, n_requests, alpha, seed)``."""
    if n_nodes <= 0 or n_requests < 0:
        raise ValueError("need n_nodes > 0 and n_requests >= 0")
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_nodes + 1, dtype=np.float64)
    p = ranks ** (-float(alpha))
    p /= p.sum()
    drawn_ranks = rng.choice(n_nodes, size=n_requests, p=p)
    node_of_rank = rng.permutation(n_nodes).astype(np.int64)
    return node_of_rank[drawn_ranks]


def poisson_arrivals(
    n_requests: int, qps: float, seed: int = 0
) -> np.ndarray:
    """``[n_requests]`` float64 cumulative arrival times (seconds) of a
    Poisson process at rate ``qps`` — the open-loop replay schedule for
    latency-under-load probes."""
    if qps <= 0:
        raise ValueError("qps must be > 0")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / qps, size=n_requests)
    return np.cumsum(gaps)


class DeltaTrace(NamedTuple):
    """A request trace with seeded EDGE-ARRIVAL events woven in (round
    17): ``requests`` is a plain `zipfian_trace` (byte-identical to the
    frozen-graph trace at the same arguments — the empty-delta parity
    legs ride that); arrival event ``i`` commits edges
    ``(edge_src[i], edge_dst[i])`` immediately BEFORE request index
    ``edge_pos[i]`` is submitted. Everything is derived from the seed, so
    probes and tests drive graph churn deterministically."""

    requests: np.ndarray   # [n_requests] int64 node ids
    edge_pos: np.ndarray   # [n_events] int64 request index per event
    edge_src: np.ndarray   # [n_events, edges_per_event] int64
    edge_dst: np.ndarray   # [n_events, edges_per_event] int64

    @property
    def n_events(self) -> int:
        return int(self.edge_pos.shape[0])

    def events(self) -> Iterator[Tuple[str, object, object]]:
        """The interleaved schedule: yields ``("edges", src_row,
        dst_row)`` then ``("request", index, node)`` in commit order —
        the one iteration a driver loop needs."""
        e = 0
        for i, node in enumerate(self.requests):
            while e < self.n_events and int(self.edge_pos[e]) == i:
                yield ("edges", self.edge_src[e], self.edge_dst[e])
                e += 1
            yield ("request", i, int(node))


def delta_interleaved_trace(
    n_nodes: int,
    n_requests: int,
    alpha: float = 0.99,
    seed: int = 0,
    edge_every: int = 32,
    edges_per_event: int = 4,
) -> DeltaTrace:
    """Weave seeded edge arrivals into a `zipfian_trace`: one event every
    ``edge_every`` requests, each carrying ``edges_per_event`` new edges.
    Sources are drawn from the PREFIX of the request trace served so far
    (arrivals correlate with live traffic — new edges land on nodes the
    cache and sketches already consider hot, the feed/fraud shape);
    destinations are uniform, self-loops nudged off. The request stream
    is byte-identical to ``zipfian_trace(n_nodes, n_requests, alpha,
    seed)`` — delta events ride a separate seeded generator, so the
    frozen-graph and streaming runs compare like for like."""
    if edge_every <= 0 or edges_per_event <= 0:
        raise ValueError("edge_every and edges_per_event must be > 0")
    requests = zipfian_trace(n_nodes, n_requests, alpha=alpha, seed=seed)
    rng = np.random.default_rng([int(seed), 0x5EED])
    pos = np.arange(edge_every, n_requests, edge_every, dtype=np.int64)
    k = pos.shape[0]
    src = np.zeros((k, edges_per_event), np.int64)
    dst = np.zeros((k, edges_per_event), np.int64)
    for i, p in enumerate(pos):
        picks = rng.integers(0, int(p), edges_per_event)
        src[i] = requests[picks]
        dst[i] = rng.integers(0, n_nodes, edges_per_event)
    loops = src == dst
    dst[loops] = (dst[loops] + 1) % n_nodes
    return DeltaTrace(requests=requests, edge_pos=pos, edge_src=src,
                      edge_dst=dst)


def trace_skew_stats(trace: np.ndarray, top_frac: float = 0.01) -> dict:
    """Observed skew of a trace: unique fraction and the request share of
    the hottest ``top_frac`` of distinct nodes (the number a cache planner
    actually wants)."""
    trace = np.asarray(trace)
    if trace.size == 0:
        return {"unique_frac": 0.0, "top_share": 0.0, "distinct": 0}
    _, counts = np.unique(trace, return_counts=True)
    counts = np.sort(counts)[::-1]
    k = max(1, int(np.ceil(top_frac * counts.size)))
    return {
        "unique_frac": counts.size / trace.size,
        "top_share": float(counts[:k].sum() / trace.size),
        "distinct": int(counts.size),
    }

"""Seeded synthetic request traces for the serving benchmarks.

Online node-prediction traffic is skewed: a handful of hub nodes (popular
items, celebrity accounts) absorb most requests. The standard model is a
Zipfian popularity law — request probability of the rank-``r`` node
proportional to ``1/r**alpha`` — with ``alpha`` around 0.6-1.1 for web/
recommendation workloads. These generators are fully seeded so every probe
and test replays bit-identically; the rank->node mapping is a seeded
permutation so "hot" nodes are scattered across the id space rather than
being ids 0..k (which would alias with the degree-ordered hot feature
prefix and flatter the cache).
"""

from __future__ import annotations

import numpy as np


def zipfian_trace(
    n_nodes: int, n_requests: int, alpha: float = 0.99, seed: int = 0
) -> np.ndarray:
    """``[n_requests]`` int64 node ids drawn Zipf(``alpha``) over
    ``n_nodes`` ranks (``alpha=0`` -> uniform). Deterministic per
    ``(n_nodes, n_requests, alpha, seed)``."""
    if n_nodes <= 0 or n_requests < 0:
        raise ValueError("need n_nodes > 0 and n_requests >= 0")
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, n_nodes + 1, dtype=np.float64)
    p = ranks ** (-float(alpha))
    p /= p.sum()
    drawn_ranks = rng.choice(n_nodes, size=n_requests, p=p)
    node_of_rank = rng.permutation(n_nodes).astype(np.int64)
    return node_of_rank[drawn_ranks]


def poisson_arrivals(
    n_requests: int, qps: float, seed: int = 0
) -> np.ndarray:
    """``[n_requests]`` float64 cumulative arrival times (seconds) of a
    Poisson process at rate ``qps`` — the open-loop replay schedule for
    latency-under-load probes."""
    if qps <= 0:
        raise ValueError("qps must be > 0")
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / qps, size=n_requests)
    return np.cumsum(gaps)


def trace_skew_stats(trace: np.ndarray, top_frac: float = 0.01) -> dict:
    """Observed skew of a trace: unique fraction and the request share of
    the hottest ``top_frac`` of distinct nodes (the number a cache planner
    actually wants)."""
    trace = np.asarray(trace)
    if trace.size == 0:
        return {"unique_frac": 0.0, "top_share": 0.0, "distinct": 0}
    _, counts = np.unique(trace, return_counts=True)
    counts = np.sort(counts)[::-1]
    k = max(1, int(np.ceil(top_frac * counts.size)))
    return {
        "unique_frac": counts.size / trace.size,
        "top_share": float(counts[:k].sum() / trace.size),
        "distinct": int(counts.size),
    }

"""Cross-host sharded serving: seed-ownership routing over the
`HostRankTable` exchange.

The single-host `ServeEngine` (rounds 8-9) turns a request stream into
efficient fixed-shape device work, but its QPS ceiling is one chip's
sample+forward throughput and one host's feature tier. The training side
already scales past one host by PARTITIONING the data and moving requests
to their owners (`HostRankTable` / `DistFeature` / `TpuComm.exchange` —
the reference's ``PartitionInfo``+``DistFeature`` multi-host layer); this
module applies the same owner-compute-then-exchange shape to serving, the
pattern the PyTorch-Direct / GPU-initiated-access line uses to keep
feature fetch off the slow path: **move the request to the data, not the
rows to the request.**

Topology of a request:

1. A front-end **router** (`DistServeEngine`) accepts single-node
   requests, dedupes/coalesces them within a flush window, and applies the
   same max_batch / max_delay_ms flush policy as the single-host engine.
2. Each router flush **splits its (deduped) seed batch by owner**
   (``global2host[seed]``, `HostRankTable` host ids) and forwards the
   per-owner sub-batches through the serve-shaped exchange
   (`TpuComm.exchange_serve`: seed ids ship out over the same all_to_all
   the feature exchange rides; LOGITS rows come back instead of feature
   rows).
3. Each **owner** runs its local pipelined `ServeEngine` — micro-batching,
   bucketed shapes, embedding cache, bounded ``max_in_flight`` window —
   against only its shard of topology + features. Aggregate QPS scales
   with hosts because each shard samples/forwards a batch ~1/H as wide,
   and per-host HBM holds ~1/H of the tables (exact 1/H when the
   partition is k-hop closed, e.g. community partitions; the halo the
   closure adds on other partitions is reported, never hidden — see
   `shard_topology_by_owner`). Under the default
   ``feature_residency="closure"`` each owner materializes its closure's
   feature rows at build time (`ClosureFeature`) so the whole shard
   dispatch is the FUSED one-program serve step — one execute call per
   owner flush; ``"exchange"`` keeps the round-10 per-flush on-demand
   feature exchange (`DistFeature`) and the split dispatch.
4. Results **scatter back by request id** and re-interleave into the
   router's dispatch-log order.

Bit-parity contract (the round-8/9 contract, extended): every served
logits row is bit-identical to the offline `inference.batch_logits` replay
of the OWNING shard's dispatch log — through a sampler over the FULL graph
(`replay_shard_oracle`), because a shard's halo-closed topology produces
draws bit-equal to the full graph's for owned seeds. At ``hosts=1`` the
engine degenerates to the single-host `ServeEngine` bit-for-bit (same
dispatch log, same key stream, same logits) at any ``max_in_flight``.

Execution modes:

- ``exchange="collective"``: sub-batches and logits ride the real
  `_a2a_ids_jit`/`_a2a_rows_jit` collectives over an H-device mesh (the
  hermetic CPU-mesh simulation of an H-host pod; on a real pod each
  process drives its own shard — `TpuComm.exchange_serve` multi-process
  mode, exercised by tests/dist_worker.py's lockstep serve mode).
- ``exchange="host"``: the router calls owner engines directly (and the
  shard features exchange through a host-side loopback). Value-identical;
  for environments without H devices.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import comm as comm_mod
from ..comm import HostRankTable, TpuComm, round_up_pow2
from ..feature import DistFeature, Feature, PartitionInfo
from ..trace import (
    NULL_JOURNAL,
    EventJournal,
    HitRateCounter,
    LatencyHistogram,
    MetricsRegistry,
    SpanRecorder,
    WorkloadConfig,
    WorkloadMonitor,
    export_chrome_trace as _export_chrome_trace,
    register_hit_rate,
)
from ..utils import CSRTopo
from .cache import EmbeddingCache
from .engine import (
    DEFAULT_TENANT,
    ServeConfig,
    ServeEngine,
    ServeResult,
    ServeStats,
    ShedError,
    _Slot,
    abandon_undrained,
    register_tenant_latency,
    shed_decision,
    weighted_drain_keys,
)

# pseudo-owner id for the local hot-set replica in a routed flush's owner
# split / dispatch log: seeds routed here are answered on the router's own
# host and never enter the serve exchange (round 15, ROADMAP item 3a)
REPLICA_HOST = -2

# bound on the hedge/shed policy logs (ring semantics, newest win): the
# conditions that fill them — sustained overload, a long-dead owner — are
# exactly when an unbounded list would leak until OOM
POLICY_LOG_CAP = 65536


class OwnerTimeout(RuntimeError):
    """A routed owner sub-batch missed its ``hedge_deadline_ms`` — the
    hedge machinery re-routes the sub-batch; the slow owner's eventual
    answer is discarded."""


def contiguous_partition(n_nodes: int, hosts: int) -> np.ndarray:
    """Balanced contiguous ``global2host`` map: host h owns rows
    ``[h*ceil(N/H), ...)`` (the same contiguous-range convention the
    row-sharded topology uses). int32 [N]."""
    if hosts < 1 or n_nodes < 1:
        raise ValueError("need hosts >= 1 and n_nodes >= 1")
    per = -(-n_nodes // hosts)
    return np.minimum(np.arange(n_nodes, dtype=np.int64) // per, hosts - 1).astype(
        np.int32
    )


def shard_topology_by_owner(
    csr_topo: CSRTopo,
    global2host: np.ndarray,
    host: int,
    hops: int,
    return_closure: bool = False,
    closure_hops: Optional[int] = None,
):
    """Host ``host``'s serving topology shard: the full-id-space CSR with
    adjacency kept ONLY for the ``hops``-hop closure of its owned nodes
    (every other row reads degree 0).

    ``hops`` is the number of EXPANSION hops whose adjacency the shard's
    sampler reads — ``len(sizes) - 1`` for an L-layer sampler, because the
    final hop's frontier is feature-gathered but never expanded. Keeping
    the closure rows bit-identical to the full graph is what makes a shard
    engine's draws for owned seeds bit-equal to a full-graph sampler on
    the same key stream (the parity contract `replay_shard_oracle` tests);
    rows outside the closure are unreachable from owned seeds, so zeroing
    them changes nothing.

    The id space stays GLOBAL (indptr keeps all N+1 rows — ~8 bytes/node,
    small next to edges and features); only the EDGE table shrinks. On a
    k-hop-closed partition (e.g. community partitions, where serving
    shards naturally align with communities) the closure adds nothing and
    each shard holds exactly its 1/H of the edges; on other partitions the
    halo is real replication and ``edge_frac`` reports it honestly.

    Returns ``(shard_topo, stats)`` with stats keys ``owned_nodes``,
    ``closure_nodes``, ``edges_kept``, ``edges_total``, ``edge_frac``;
    with ``return_closure=True``, ``(shard_topo, stats, closure_ids)`` —
    the sorted global ids of the ``closure_hops``-hop closure (default:
    ``hops``). `ClosureFeature` wants ``closure_hops = hops + 1``: the
    final hop's LEAF frontier is feature-gathered but never expanded, so
    leaves live one hop beyond the adjacency closure — that deeper set is
    exactly every node a shard engine can ever gather a row for.
    """
    indptr = np.asarray(csr_topo.indptr, np.int64)
    indices = np.asarray(csr_topo.indices, np.int64)
    g2h = np.asarray(global2host)
    n = indptr.shape[0] - 1
    if g2h.shape[0] != n:
        raise ValueError(f"global2host has {g2h.shape[0]} rows, graph has {n}")
    owned = np.nonzero(g2h == host)[0]
    closure = np.zeros(n, bool)
    closure[owned] = True
    hops = max(int(hops), 0)
    feat_hops = hops if closure_hops is None else max(int(closure_hops), hops)
    # edge-parallel BFS (vectorized — a per-frontier-node python loop is
    # O(minutes) at products scale): src id per CSR slot built once, each
    # hop masks the frontier's edges and uniques their endpoints. The
    # ADJACENCY closure is captured at depth ``hops``; the BFS may continue
    # to ``closure_hops`` for the returned (feature) closure ids.
    src_per_edge = np.repeat(
        np.arange(n, dtype=np.int64), (indptr[1:] - indptr[:-1])
    )
    frontier_mask = closure.copy()
    topo_closure = closure.copy() if hops == 0 else None
    for hop in range(feat_hops):
        if not frontier_mask.any():
            break
        nxt = np.unique(indices[frontier_mask[src_per_edge]])
        nxt = nxt[~closure[nxt]]
        if nxt.size == 0:
            break
        closure[nxt] = True
        frontier_mask = np.zeros(n, bool)
        frontier_mask[nxt] = True
        if hop + 1 == hops:
            topo_closure = closure.copy()
    if topo_closure is None:  # BFS exhausted the graph before `hops`
        topo_closure = closure.copy()
    deg = np.where(topo_closure, indptr[1:] - indptr[:-1], 0)
    new_indptr = np.zeros(n + 1, np.int64)
    np.cumsum(deg, out=new_indptr[1:])
    keep_edge = topo_closure[src_per_edge]
    new_indices = indices[keep_edge]
    new_weights = (
        None
        if csr_topo.edge_weights is None
        else np.asarray(csr_topo.edge_weights, np.float32)[keep_edge]
    )
    shard = CSRTopo(indptr=new_indptr, indices=new_indices, edge_weights=new_weights)
    stats = {
        "owned_nodes": int(owned.shape[0]),
        "closure_nodes": int(topo_closure.sum()),
        "feature_closure_nodes": int(closure.sum()),
        "edges_kept": int(new_indices.shape[0]),
        "edges_total": int(indices.shape[0]),
        "edge_frac": (
            float(new_indices.shape[0]) / float(max(indices.shape[0], 1))
        ),
    }
    if return_closure:
        return shard, stats, np.nonzero(closure)[0]
    return shard, stats


def shard_topology_for_seeds(
    csr_topo: CSRTopo,
    seed_ids: np.ndarray,
    hops: int,
    closure_hops: Optional[int] = None,
):
    """`shard_topology_by_owner` for an EXPLICIT seed set instead of an
    ownership map: the hops-hop halo-closure topology of ``seed_ids``
    (every other row reads degree 0), in the GLOBAL id space. This is the
    hot-set replica's topology (round 15): a sampler over it draws
    bit-identically to a full-graph sampler for the replicated seeds —
    the same closure argument the owner shards ride. Returns
    ``(shard_topo, stats, closure_ids)``."""
    n = csr_topo.indptr.shape[0] - 1
    seed_ids = np.asarray(seed_ids, np.int64)
    if seed_ids.size and (seed_ids.min() < 0 or seed_ids.max() >= n):
        raise ValueError(f"seed ids outside [0, {n})")
    mask = np.ones(n, np.int32)  # host 1 = everyone else
    mask[seed_ids] = 0           # host 0 = the replicated set
    return shard_topology_by_owner(
        csr_topo, mask, 0, hops, return_closure=True,
        closure_hops=closure_hops,
    )


class LoopbackComm:
    """Host-side stand-in for `TpuComm` in ``exchange="host"`` mode: the
    same `register_local_table` / `exchange` surface, answered by direct
    numpy indexing instead of collectives. Value-identical to the wire
    path (the collectives move bytes, they never transform them), so shard
    features built over it serve bit-identical rows — it just measures
    nothing about the interconnect."""

    def __init__(self, hosts: int):
        self.table = HostRankTable(hosts, 1)
        self._blocks: Dict[int, np.ndarray] = {}

    def register_local_table(self, host: int, rows: np.ndarray) -> None:
        self._blocks[host] = np.asarray(rows, np.float32)

    def exchange(self, host2ids, budget=None):
        res = []
        for j, ids in enumerate(host2ids):
            ids = np.asarray(ids, np.int64)
            res.append(self._blocks[j][ids] if ids.size else None)
        return res


class _ShardFeature:
    """The shard engine's feature view: clip global ids like the raw-table
    `inference.lookup_features` path (sampled ``n_id`` may carry padding
    lanes), then answer owned rows from the local 1/H block and halo rows
    through the feature exchange (`DistFeature`). The clip is what keeps a
    shard engine's forward bit-identical to a raw-full-table engine's on
    the same sample."""

    def __init__(self, dist: DistFeature, n_nodes: int):
        self._dist = dist
        self._n = n_nodes

    @property
    def shape(self):
        return (self._n, self._dist.feature.dim)

    @property
    def dim(self) -> int:
        return self._dist.feature.dim

    @property
    def tier_counter(self):
        """Delegate the observe-only tier tap to the LOCAL feature shard
        (round 14): the owner engine's workload monitor then attributes
        the owned-rows gather per tier — hbm/host/disk of the shard's
        own store; exchanged halo rows are the peer's tiers to count."""
        return self._dist.feature.tier_counter

    @tier_counter.setter
    def tier_counter(self, counter) -> None:
        self._dist.feature.tier_counter = counter

    @property
    def row_tap(self):
        return self._dist.feature.row_tap

    @row_tap.setter
    def row_tap(self, tap) -> None:
        self._dist.feature.row_tap = tap

    def __getitem__(self, n_id):
        ids = np.clip(np.asarray(n_id), 0, self._n - 1)
        return self._dist[ids]


class ClosureFeature:
    """Owner-resident serve features over GLOBAL ids — the fusable shard
    feature (``feature_residency="closure"``).

    Holds the feature rows of the shard's whole ``hops``-hop closure
    (owned + halo — exactly the rows the per-flush `DistFeature` exchange
    would have fetched, materialized ONCE at build time) plus an ``[N]``
    int32 global→row map, so the owner's gather is a pure in-jit
    take-of-take and the FUSED one-dispatch serve program applies
    (`inference.feature_gather_spec` reads `jit_gather_spec`). On a
    k-hop-closed partition the closure adds nothing and residency is
    exactly 1/H of the table; elsewhere the halo is real replication,
    reported in ``shard_topo_stats`` (``closure_nodes`` vs ``owned_nodes``)
    — never hidden.

    Out-of-closure ids map to -1 and clip to row 0: such lanes are
    unreachable from owned seeds (the closure IS the sampler's reachable
    set), so they only ever occur in masked pad lanes the model's
    aggregation zeroes out — the same guarantee every padded pipeline here
    rides. Host ``__getitem__`` runs the identical clip/map/clip/take
    arithmetic, so split-path dispatches and parity replays are
    value-identical to the fused gather."""

    def __init__(self, rows: np.ndarray, local_map: np.ndarray):
        self._rows = np.ascontiguousarray(np.asarray(rows, np.float32))
        self._map = np.asarray(local_map, np.int32)
        if self._rows.ndim != 2 or self._map.ndim != 1:
            raise ValueError("ClosureFeature wants rows [C, D] and map [N]")
        # hosts=1 (closure == everything): the map is the identity, so the
        # fused gather collapses to the plain-table program — the hosts=1
        # engine then runs the EXACT executable the single-host engine
        # runs (bitwise degeneration by construction, and one fewer
        # compiled program shape)
        self._identity = self._map.shape[0] == self._rows.shape[0] and bool(
            np.array_equal(self._map, np.arange(self._map.shape[0], dtype=np.int32))
        )
        self._dev: Optional[Tuple] = None

    @property
    def shape(self):
        return (self._map.shape[0], self._rows.shape[1])

    @property
    def dim(self) -> int:
        return self._rows.shape[1]

    @property
    def resident_rows(self) -> int:
        return self._rows.shape[0]

    def jit_gather_spec(self):
        import jax.numpy as jnp

        if self._dev is None:
            self._dev = (
                jnp.asarray(self._rows),
                None if self._identity else jnp.asarray(self._map),
            )
        return self._dev

    def __getitem__(self, n_id):
        import jax.numpy as jnp

        ids = np.clip(np.asarray(n_id), 0, self._map.shape[0] - 1)
        loc = np.clip(self._map[ids], 0, self._rows.shape[0] - 1)
        return jnp.asarray(self._rows[loc])


@dataclass
class DistServeConfig:
    """Router knobs (per-shard engine knobs ride ``shard_config``).

    hosts          : number of serving shards (HostRankTable hosts).
    max_batch      : router flush width — unique seeds drained per flush,
                     BEFORE the owner split (per-shard sub-batches are
                     ~max_batch/hosts on uniform traffic; the probe's
                     width-shrink acceptance reads this).
    max_delay_ms   : router flush-age policy, same semantics as
                     `ServeConfig.max_delay_ms`.
    max_in_flight  : router in-flight window (concurrent routed flushes).
    exchange       : "collective" (ids/logits ride the mesh all_to_all),
                     "host" (direct owner calls + loopback feature
                     exchange), or "auto" (collective when the backend has
                     >= hosts devices).
    budget         : per-owner seed-id budget of the serve exchange (static
                     collective shape); default pow2(max_batch) — a whole
                     router flush to one owner always fits.
    shard_config   : template `ServeConfig` for the per-shard engines
                     (default: the router's max_batch/max_in_flight with
                     the delay policy irrelevant — the router drives shard
                     flushes synchronously). ``record_dispatches`` on the
                     shard engines is what the parity replay reads.
    cache_entries  : per-shard embedding-cache rows at the OWNERS (so the
                     backing cache splits by ownership).
    router_cache_entries : front-end result-cache rows (default: same as
                     ``cache_entries``; 0 disables). Repeat requests for a
                     node already served under the current params version
                     are answered AT THE ROUTER — no routing, no exchange
                     bytes, no owner work. Same get-at-submit /
                     put-at-resolve / invalidate-on-update sequencing as
                     `ServeEngine`'s cache, which is what makes the
                     ``hosts=1`` engine bit-identical to the single-host
                     engine INCLUDING cache behavior (identical LRU
                     evolution -> identical flush composition -> identical
                     key stream) — PROVIDED the cache never evicts (working
                     set <= capacity). Under eviction pressure the router
                     and owner caches can diverge in LRU state (the owner
                     cache only sees router misses), so an owner may answer
                     a router-missed repeat from ITS cache where the
                     single-host engine would re-dispatch — flush
                     composition then differs. Served rows stay bit-equal
                     to the owning shard's replay oracle either way (a
                     cached row was computed by a logged dispatch).
    clock          : injectable monotonic clock shared with shard engines.
    record_dispatches : keep the router's (seeds, per-owner split) log.
    feature_residency : "closure" (default) materializes each owner's
                     feature rows for its whole k-hop closure at BUILD time
                     (`ClosureFeature`: the rows the per-flush DistFeature
                     exchange would have fetched, fetched once), making the
                     owner gather in-jit so shard engines run the FUSED
                     one-dispatch serve program; "exchange" keeps the
                     round-10 on-demand feature exchange (owned rows local,
                     halo rows over the wire per flush — shard engines then
                     serve on the split path). Value-identical; residency
                     trades halo-row memory for per-flush exchange work.
    late_admission : admit late-arriving seeds into a routed flush that is
                     assembled but still waiting for a window slot (up to
                     ``max_batch``), mirroring `ServeConfig.late_admission`.
    journal_events : router-side `trace.EventJournal` capacity (0 =
                     disabled). The default shard config inherits it, so
                     every owner engine journals too; `fleet_snapshot` /
                     `export_chrome_trace` merge the owner journals
                     deterministically (sorted host, dispatch-index order
                     within — the same discipline as the stats merges).
                     Observe-only, same contract as
                     `ServeConfig.journal_events`.
    workload       : a `trace.WorkloadConfig` enables round-13 workload
                     telemetry at the ROUTER (access-frequency sketches
                     over every submitted seed, per-owner routed
                     sub-batch widths + flush/exchange latency quantiles,
                     imbalance + straggler stats) and — via the default
                     shard config — at every owner engine (owner-side
                     sketches, cache taps, tier attribution).
                     `workload_report()` / `fleet_registry()` are the
                     read side. Observe-only, replay-deterministic decay
                     ticks on the router's dispatch index, same contract
                     as `ServeConfig.workload`.
    """

    hosts: int = 2
    max_batch: int = 64
    max_delay_ms: float = 2.0
    max_in_flight: int = 2
    exchange: str = "auto"
    budget: Optional[int] = None
    shard_config: Optional[ServeConfig] = None
    cache_entries: int = 100_000
    router_cache_entries: Optional[int] = None
    clock: Callable[[], float] = time.monotonic
    flush_poll_ms: float = 0.2
    record_dispatches: bool = False
    feature_residency: str = "closure"
    late_admission: bool = True
    journal_events: int = 0
    workload: Optional[WorkloadConfig] = None
    # -- round-15 fleet policies (ROADMAP item 3; docs/api.md "Fleet
    # serving") -----------------------------------------------------------
    # replicate_top_k: hot-set replication head size — `refresh_replicas()`
    # mirrors the k hottest seeds (router workload sketch; k priced by
    # scaling.skew_table) onto the router's own host, so head traffic is
    # answered locally and never enters comm.exchange_serve. 0 = off.
    replicate_top_k: int = 0
    # hedge_deadline_ms: per-owner deadline on routed sub-batches
    # (exchange="host" mode, where owner legs are individually
    # addressable). A leg that misses it re-routes to the full-graph
    # fallback / the replica; the slow owner's answer is discarded.
    # 0 = no deadline (errors still fail over when a target exists).
    hedge_deadline_ms: float = 0.0
    # full_graph_fallback: build() keeps one full-topology/full-feature
    # engine on the router's host as the degraded-mode hedge target — any
    # seed can fail over to it (the replica covers only the hot head).
    full_graph_fallback: bool = False
    # eject_after / eject_backoff_flushes: an owner failing this many
    # CONSECUTIVE sub-batches is ejected (routed straight to the hedge
    # target, no deadline burned) until this many router dispatch indices
    # pass — then it is probed again (half-open). Flush-indexed, never
    # wall time, so ejection decisions replay deterministically.
    eject_after: int = 2
    eject_backoff_flushes: int = 16
    # fault_injector: a `serve.faults.FaultInjector` exercising the
    # host-mode owner legs — deterministic (owner, dispatch-index) keyed
    # kill/error/stall, the proof harness for everything above.
    fault_injector: Optional[object] = None
    # per-tenant admission (same semantics as the ServeConfig fields;
    # applied at the ROUTER — the fleet's admission point)
    tenant_weights: Optional[Dict[str, float]] = None
    max_queue_depth: int = 0
    drain_deadline_s: float = 30.0
    # round-14 adaptive tier knobs, inherited by every owner engine via
    # the default shard config (same semantics as the ServeConfig
    # fields); `DistServeEngine.adapt_tiers` drives one fenced pass per
    # owner, `start()` runs it fleet-wide when tier_adapt_every_s > 0
    tier_promote_batch: int = 64
    tier_promote_min: float = 2.0
    tier_hysteresis: float = 1.25
    tier_adapt_every_s: float = 0.0

    def resolved_shard_config(self) -> ServeConfig:
        if self.shard_config is not None:
            return self.shard_config
        return ServeConfig(
            max_batch=self.max_batch,
            max_delay_ms=self.max_delay_ms,
            max_in_flight=self.max_in_flight,
            cache_entries=self.cache_entries,
            clock=self.clock,
            record_dispatches=self.record_dispatches,
            late_admission=self.late_admission,
            journal_events=self.journal_events,
            workload=self.workload,
            tier_promote_batch=self.tier_promote_batch,
            tier_promote_min=self.tier_promote_min,
            tier_hysteresis=self.tier_hysteresis,
        )


@dataclass
class DistServeStats:
    """Router-side counters; `DistServeEngine.aggregate_stats` merges the
    per-shard `ServeStats` on top (via the ``merge`` family in
    `quiver_tpu.trace`). ``exchange_id_bytes``/``exchange_logit_bytes``
    count the GLOBAL collective payloads (H*H*L ids, H*H*L*C logits per
    routed flush in collective mode) — the wire term
    `scaling.serve_table(hosts=...)` prices."""

    requests: int = 0
    coalesced: int = 0
    router_dispatches: int = 0
    routed_seeds: int = 0
    late_admitted: int = 0
    # round-15 fleet-policy counters: replica_hits counts seeds answered
    # by the local hot-set replica (never entered the exchange); hedges /
    # hedged_seeds count owner sub-batches (and their seeds) re-routed to
    # a failover target, split by cause (deadline miss vs owner error vs
    # routed-while-ejected); owner_ejections counts backoff entries;
    # shed / request_errors / undrained mirror the ServeStats fields.
    replica_hits: int = 0
    hedges: int = 0
    hedged_seeds: int = 0
    hedge_timeouts: int = 0
    hedge_errors: int = 0
    hedge_ejected: int = 0
    hedge_failed: int = 0       # failovers with no (working) target
    owner_ejections: int = 0
    shed: int = 0
    request_errors: int = 0
    undrained: int = 0
    inflight_peak: int = 0
    sub_batches: Dict[int, int] = field(default_factory=dict)
    sub_batch_seeds: Dict[int, int] = field(default_factory=dict)
    exchange_id_bytes: int = 0
    exchange_logit_bytes: int = 0
    router_cache: HitRateCounter = field(default_factory=HitRateCounter)
    latency: LatencyHistogram = field(default_factory=LatencyHistogram)
    tenant_latency: Dict[str, LatencyHistogram] = field(default_factory=dict)
    spans: SpanRecorder = field(default_factory=SpanRecorder)

    def tenant_hist(self, tenant: str) -> LatencyHistogram:
        from .engine import tenant_latency_hist

        return tenant_latency_hist(self.tenant_latency, tenant)

    def mean_sub_batch_width(self) -> Dict[int, float]:
        return {
            h: self.sub_batch_seeds[h] / n
            for h, n in self.sub_batches.items()
            if n
        }

    def snapshot(self) -> Dict[str, object]:
        return {
            "requests": self.requests,
            "coalesced": self.coalesced,
            "router_dispatches": self.router_dispatches,
            "routed_seeds": self.routed_seeds,
            "late_admitted": self.late_admitted,
            "replica_hits": self.replica_hits,
            "hedges": self.hedges,
            "hedged_seeds": self.hedged_seeds,
            "hedge_timeouts": self.hedge_timeouts,
            "hedge_errors": self.hedge_errors,
            "hedge_ejected": self.hedge_ejected,
            "hedge_failed": self.hedge_failed,
            "owner_ejections": self.owner_ejections,
            "shed": self.shed,
            "request_errors": self.request_errors,
            "undrained": self.undrained,
            "inflight_peak": self.inflight_peak,
            "sub_batches": dict(self.sub_batches),
            "mean_sub_batch_width": self.mean_sub_batch_width(),
            "exchange_id_bytes": self.exchange_id_bytes,
            "exchange_logit_bytes": self.exchange_logit_bytes,
            "router_cache": self.router_cache.snapshot(),
            "latency": self.latency.snapshot(),
            "tenant_latency": {
                t: self.tenant_latency[t].snapshot()
                for t in sorted(self.tenant_latency)
            },
            "overlap": self.spans.overlap_summary(),
        }


class _RoutedFlush:
    """Per-flush router state between assemble and resolve. ``bucket`` is
    the admission cap (the router pads nothing, so its "pad slack" is the
    drained width up to ``max_batch``); the owner split is computed at SEAL
    time so late-admitted seeds route with their flush.

    ``error`` poisons the WHOLE flush (assemble/seal failures, a
    collective-exchange abort); ``slot_errors`` maps key POSITIONS to
    per-request exceptions — the round-15 isolation contract: a failed
    owner sub-batch resolves only its own slots with the error, every
    other slot resolves normally, and `flush()` does not re-raise."""

    __slots__ = ("keys", "slots", "split", "bucket", "error", "slot_errors",
                 "fid")

    def __init__(self, keys, slots, split):
        self.keys = keys
        self.slots = slots
        self.split = split  # [(host, ids ndarray, positions ndarray)]
        self.bucket = 0
        self.error: Optional[BaseException] = None
        self.slot_errors: Dict[int, BaseException] = {}
        self.fid = -1  # journal flush id (router dispatch-log index)


class _HotReplica:
    """The router-local hot-set replica (round 15): a full `ServeEngine`
    over the replicated seeds' halo-closure topology + feature rows —
    the mirror of Quiver's ``p2p_clique_replicate`` hot-prefix applied to
    serving. ``ids`` is the sorted replicated seed set; ``id_set`` the
    O(1) membership view the hedge path consults."""

    __slots__ = ("engine", "ids", "id_set", "version", "stats")

    def __init__(self, engine: ServeEngine, ids: np.ndarray, version: int,
                 stats: Dict[str, float]):
        self.engine = engine
        self.ids = np.asarray(ids, np.int64)
        self.id_set = frozenset(int(x) for x in self.ids)
        self.version = version
        self.stats = stats


class DistServeEngine:
    """Seed-ownership-sharded serving front end (module docstring has the
    design; docs/api.md "Distributed serving" the contract). Typical use::

        dist = DistServeEngine.build(
            model, params, csr_topo, feat, sizes=[8, 8], hosts=2,
            config=DistServeConfig(max_batch=32),
        )
        dist.warmup()
        out = dist.predict(node_ids)     # routed, owner-served, re-merged

    The constructor takes prebuilt shard engines keyed by host (`build`
    does the partitioning); multi-process deployments construct with only
    their own host's engine and a `TpuComm` whose serve answerer is
    registered, then drive lockstep flushes (tests/dist_worker.py serve
    mode)."""

    def __init__(
        self,
        engines: Dict[int, ServeEngine],
        global2host: np.ndarray,
        out_dim: int,
        config: Optional[DistServeConfig] = None,
        comm: Optional[TpuComm] = None,
        shard_topo_stats: Optional[Dict[int, Dict[str, float]]] = None,
    ):
        self.config = config or DistServeConfig()
        if self.config.max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        mode = self.config.exchange
        if mode not in ("auto", "collective", "host"):
            raise ValueError(f"unknown exchange mode {mode!r}")
        if mode == "auto":
            mode = "collective" if comm is not None else "host"
        if mode == "collective" and comm is None:
            raise ValueError("exchange='collective' needs a TpuComm")
        if self.config.fault_injector is not None and mode != "host":
            raise ValueError(
                "fault_injector exercises the per-owner host-mode dispatch "
                "legs (the collective is one launch and cannot fail "
                "per-owner); build with exchange='host'"
            )
        self.exchange_mode = mode
        self.engines = dict(engines)
        self.hosts = self.config.hosts
        self.global2host = np.asarray(global2host, np.int32)
        self.out_dim = int(out_dim)
        self.comm = comm
        self.shard_topo_stats = shard_topo_stats or {}
        self._budget = self.config.budget or round_up_pow2(self.config.max_batch)
        self._clock = self.config.clock
        self.stats = DistServeStats()
        self.journal = (
            EventJournal(self.config.journal_events, clock=self._clock)
            if self.config.journal_events > 0
            else NULL_JOURNAL
        )
        self._next_rid = 0     # journal request ids (guarded by _lock)
        self._flush_index = 0  # router dispatch-log index (guarded by _seq)
        self.tier_adapt_errors = 0  # failed fleet tier-adaptation passes
        # round-13 router-side workload telemetry (observe-only): the
        # router sees EVERY submitted seed, so its sketch is the fleet's
        # access-frequency view; per-owner load/latency land here too
        self.workload = (
            WorkloadMonitor(self.config.workload, clock=self._clock)
            if self.config.workload is not None
            else None
        )
        rc = self.config.router_cache_entries
        self.cache = EmbeddingCache(
            self.config.cache_entries if rc is None else rc,
            counters=self.stats.router_cache,
        )
        if self.workload is not None:
            self.cache.workload = self.workload
        self.params_version = 0
        self.dispatch_log: List[Tuple[np.ndarray, List[Tuple[int, np.ndarray]]]] = []
        self._pending: Dict[int, _Slot] = {}
        self._inflight: Dict[int, _Slot] = {}
        import collections

        # round-15 fleet-policy state -------------------------------------
        # per-tenant admission (guarded by _lock; mirrors ServeEngine).
        # Policy logs are BOUNDED rings (newest win) — sustained overload
        # or a long-dead owner is exactly when they fill, and an unbounded
        # list there would leak until OOM
        self._pending_tenant: Dict[str, int] = {}
        self.shed_log = collections.deque(maxlen=POLICY_LOG_CAP)
        # hot-set replica (swapped only under the update_params fence) +
        # the full-graph failover engine (built by `build` on request)
        self.replica: Optional[_HotReplica] = None
        self.replica_version = 0
        # retired replica engines keep their dispatch logs so the fleet
        # replay oracle can still vouch for rows they served pre-refresh
        self._retired_replicas: List[ServeEngine] = []
        self.fallback: Optional[ServeEngine] = None
        self._params = None                # tracked for replica rebuilds
        self._replica_materials: Optional[Dict[str, object]] = None
        # per-owner health for hedged dispatch: consecutive failures +
        # the dispatch index an ejection started at (-1 = serving);
        # flush-indexed backoff keeps the state machine replayable
        self._owner_health: Dict[int, Dict[str, int]] = {}
        # deterministic hedge log [(fid, owner, reason, target)] — append
        # order may interleave across in-flight flushes, read the sorted
        # `hedge_events()` view for replay comparison; bounded like
        # shed_log (a dead owner with no failover appends per flush)
        self.hedge_log = collections.deque(maxlen=POLICY_LOG_CAP)
        # abandoned (deadline-missed) leg threads per owner, guarded by
        # _lock: while any is still alive the owner is treated as wedged
        # and no new leg is spawned — growth is bounded by max_in_flight
        # per wedge episode, never the life of the router
        self._abandoned_legs: Dict[int, List[threading.Thread]] = {}
        self.faults = self.config.fault_injector
        self._open: Optional[_RoutedFlush] = None
        self._lock = threading.Lock()
        self._fence = threading.Condition(self._lock)
        self._seq = threading.Lock()
        self._window = threading.BoundedSemaphore(self.config.max_in_flight)
        self._inflight_flushes = 0
        self._threads: List[threading.Thread] = []
        self._running = False
        if mode == "collective":
            # the serve exchange's static shape: every host must agree
            self.comm.static_budget = self._budget
            for h, eng in self.engines.items():
                self.comm.register_serve_answerer(h, self._make_answerer(h))

    # -- construction ------------------------------------------------------

    @classmethod
    def build(
        cls,
        model,
        params,
        csr_topo: CSRTopo,
        feat: np.ndarray,
        sizes: Sequence[int],
        *,
        hosts: int,
        config: Optional[DistServeConfig] = None,
        global2host: Optional[np.ndarray] = None,
        sampler_seed: int = 0,
        sampler_mode: str = "TPU",
        sampler_kw: Optional[dict] = None,
        out_dim: Optional[int] = None,
        mesh=None,
        feature_kw: Optional[dict] = None,
    ) -> "DistServeEngine":
        """Partition ``csr_topo``/``feat`` by seed ownership and assemble
        the router + H shard engines in one process (the hermetic pod
        simulation). Every shard sampler is born with the SAME
        ``sampler_seed`` — each shard's key stream then matches a freshly
        born single-host sampler's, which is what lets the parity oracle
        replay any shard's dispatch log through a full-graph sampler."""
        import jax

        from ..pyg.sage_sampler import GraphSageSampler

        config = config or DistServeConfig(hosts=hosts)
        if config.hosts != hosts:
            raise ValueError(f"config.hosts={config.hosts} != hosts={hosts}")
        feat = np.asarray(feat, np.float32)
        n = csr_topo.indptr.shape[0] - 1
        if global2host is None:
            global2host = contiguous_partition(n, hosts)
        out_dim = out_dim if out_dim is not None else getattr(model, "out_dim", None)
        if out_dim is None:
            raise ValueError("pass out_dim= (model has no out_dim attribute)")
        mode = config.exchange
        if mode == "auto":
            mode = "collective" if len(jax.devices()) >= hosts else "host"
        comm = None
        feat_comms: List[object] = []
        if mode == "collective":
            if mesh is None:
                from jax.sharding import Mesh

                devs = jax.devices()
                if len(devs) < hosts:
                    raise ValueError(
                        f"exchange='collective' needs >= {hosts} devices "
                        f"(got {len(devs)}); use exchange='host'"
                    )
                mesh = Mesh(np.array(devs[:hosts]), ("serve_host",))
            comm = TpuComm(
                rank=0, world_size=hosts, hosts=hosts, mesh=mesh, axis="serve_host"
            )
        residency = config.feature_residency
        if residency not in ("closure", "exchange"):
            raise ValueError(f"unknown feature_residency {residency!r}")
        if feature_kw and residency != "exchange":
            # tiered owner features (disk/adaptive knobs) gather host-side
            # through Feature; the closure residency is a dense in-jit
            # table by construction, so the knobs would be silently dead
            raise ValueError(
                "feature_kw (tiered owner features) requires "
                "feature_residency='exchange'"
            )
        # feature-exchange budget ("exchange" residency only): a shard
        # forward gathers up to the final padded n_id width of the largest
        # bucket, all of which could be remote in the worst case
        from ..ops.sample import pad_widths

        shard_cfg = config.resolved_shard_config()
        kw = dict(sampler_kw or {})
        widths = pad_widths(
            max(shard_cfg.resolved_buckets()), sizes, kw.get("caps")
        )
        feat_budget = round_up_pow2(widths[-1])
        engines: Dict[int, ServeEngine] = {}
        topo_stats: Dict[int, Dict[str, float]] = {}
        for h in range(hosts):
            # adjacency closure: len(sizes)-1 expansion hops; FEATURE
            # closure one deeper — the last hop's leaves are gathered but
            # never expanded (shard_topology_by_owner docstring)
            topo_h, st, closure_ids = shard_topology_by_owner(
                csr_topo, global2host, h, hops=len(sizes) - 1,
                return_closure=True, closure_hops=len(sizes),
            )
            topo_stats[h] = st
            sampler = GraphSageSampler(
                topo_h, sizes=sizes, mode=sampler_mode, seed=sampler_seed, **kw
            )
            if residency == "closure":
                # materialize the closure's rows ONCE (the rows the
                # per-flush exchange would fetch) — the owner gather is
                # then in-jit, so the shard engine serves on the FUSED
                # one-dispatch program; residency is honest: closure ==
                # owned (exactly 1/H) on k-hop-closed partitions, the halo
                # elsewhere is already reported in topo_stats
                local_map = np.full(n, -1, np.int32)
                local_map[closure_ids] = np.arange(
                    closure_ids.shape[0], dtype=np.int32
                )
                shard_feat = ClosureFeature(feat[closure_ids], local_map)
            else:
                owned = np.nonzero(global2host == h)[0]
                fkw = dict(feature_kw or {})
                if fkw.get("disk_path"):
                    # per-owner flat files: "{host}" in the template keeps
                    # H shards from clobbering one backing file
                    fkw["disk_path"] = fkw["disk_path"].format(host=h)
                f = Feature(rank=0, device_list=[0],
                            **{"device_cache_size": 0, **fkw})
                f.from_cpu_tensor(feat[owned])
                f.set_local_order(owned)
                if mode == "collective":
                    fcomm = TpuComm(
                        rank=h, world_size=hosts, hosts=hosts, mesh=mesh,
                        axis="serve_host",
                    )
                    fcomm.static_budget = feat_budget
                else:
                    fcomm = LoopbackComm(hosts)
                feat_comms.append(fcomm)
                info = PartitionInfo(
                    device=0, host=h, hosts=hosts, global2host=global2host
                )
                shard_feat = _ShardFeature(DistFeature(f, info, fcomm), n)
            engines[h] = ServeEngine(model, params, sampler, shard_feat, shard_cfg)
        # single-controller mode: every feature comm holds every block (a
        # real pod registers only its own — the 1/H HBM claim is about the
        # per-process resident set, which IS one block per host there)
        for h in range(hosts):
            block = np.asarray(feat[np.nonzero(global2host == h)[0]], np.float32)
            for fcomm in feat_comms:
                fcomm.register_local_table(h, block)
        dist = cls(
            engines, global2host, out_dim, config=config, comm=comm,
            shard_topo_stats=topo_stats,
        )
        # round-15 fleet policies need build-time materials: the replica
        # is rebuilt from the full graph/table on every refresh, and the
        # fallback engine IS a full-graph single-host engine (the degraded
        # path any seed can fail over to). Multi-process constructions
        # (bare __init__) have neither — they hold only their own shard.
        dist._params = params
        dist._replica_materials = {
            "model": model, "csr_topo": csr_topo, "feat": feat,
            "sizes": tuple(sizes), "sampler_mode": sampler_mode,
            "sampler_seed": sampler_seed, "sampler_kw": dict(kw),
            "shard_config": shard_cfg,
        }
        if config.full_graph_fallback:
            fb_sampler = GraphSageSampler(
                csr_topo, sizes=sizes, mode=sampler_mode, seed=sampler_seed,
                **kw,
            )
            dist.fallback = ServeEngine(model, params, fb_sampler, feat,
                                        shard_cfg)
        return dist

    def _make_answerer(self, host: int):
        """The owner-side hook of the serve exchange: ids arrive
        requester-major [H, L] (-1-padded), each requester's valid lanes go
        through the owner engine's FULL local path (cache, coalescing,
        micro-batching, window), invalid lanes return zeros."""

        def answer(recv_ids: np.ndarray) -> np.ndarray:
            recv_ids = np.asarray(recv_ids)
            out = np.zeros(
                (recv_ids.shape[0], recv_ids.shape[1], self.out_dim), np.float32
            )
            for req in range(recv_ids.shape[0]):
                valid = recv_ids[req] >= 0
                if valid.any():
                    ids = recv_ids[req][valid].astype(np.int64)
                    out[req, valid] = np.asarray(self.engines[host].predict(ids))
            return out

        return answer

    # -- request path ------------------------------------------------------

    def submit(self, node_id: int,
               tenant: Optional[str] = None) -> ServeResult:
        """Enqueue one request: the front-end result cache answers repeats
        of already-served nodes outright (no routing, no exchange bytes),
        then the same dedup/coalesce semantics as `ServeEngine.submit`
        apply to the rest. ``tenant`` drives the round-15 per-tenant
        admission exactly as on the single-host engine (weighted flush
        quotas, deterministic queue-depth shedding, per-tenant latency).
        KEEP IN LOCKSTEP with `ServeEngine.submit` — the hosts=1
        bit-parity contract depends on the two front ends making
        identical cache/coalesce decisions per request, and
        `test_shards1_bit_equal_single_host_engine` pins it."""
        key = int(node_id)
        if not 0 <= key < self.global2host.shape[0]:
            raise ValueError(
                f"node id {key} outside [0, {self.global2host.shape[0]})"
            )
        tenant = DEFAULT_TENANT if tenant is None else str(tenant)
        now = self._clock()
        need_flush = False
        jr = self.journal
        wl = self.workload
        with self._lock:
            self.stats.requests += 1
            if wl is not None:
                wl.observe_seed(key)  # observe-only frequency tap
            cached = self.cache.get(key, self.params_version)
            if cached is not None:
                ms = (self._clock() - now) * 1e3
                self.stats.latency.record_ms(ms)
                self.stats.tenant_hist(tenant).record_ms(ms)
                jr.emit("cache_hit", -1, -1, key)
                return ServeResult(value=cached)
            slot = self._pending.get(key) or self._inflight.get(key)
            if slot is not None and slot.version == self.params_version:
                self.stats.coalesced += 1
                jr.emit("coalesce", slot.rid, -1, key)
            else:
                if shed_decision(
                    len(self._pending), self._pending_tenant.get(tenant, 0),
                    tenant, self.config.max_queue_depth,
                    self.config.tenant_weights,
                ):
                    self.stats.shed += 1
                    self.shed_log.append((self.stats.requests, tenant, key))
                    jr.emit("shed", -1, -1, key)
                    return ServeResult(error=ShedError(
                        f"router queue depth {len(self._pending)} >= "
                        f"{self.config.max_queue_depth} and tenant "
                        f"{tenant!r} is at its weighted quota"
                    ))
                rid = -1
                if jr.enabled:
                    rid = self._next_rid
                    self._next_rid += 1
                slot = _Slot(key, self.params_version, now, rid=rid,
                             tenant=tenant)
                fl = self._open
                if fl is not None and len(fl.keys) < fl.bucket:
                    # late admission into the routed flush still waiting
                    # for its window slot (owner split happens at seal)
                    fl.keys.append(key)
                    fl.slots.append(slot)
                    self._inflight[key] = slot
                    self.stats.late_admitted += 1
                    jr.emit("late_admit", rid, fl.fid, key)
                else:
                    self._pending[key] = slot
                    self._pending_tenant[tenant] = (
                        self._pending_tenant.get(tenant, 0) + 1
                    )
                    jr.emit("submit", rid, -1, key)
            slot.waiters.append((now, tenant))
            if len(self._pending) >= self.config.max_batch:
                need_flush = True
        if need_flush:
            self.flush()
        return ServeResult(slot=slot)

    def predict(self, node_ids, timeout: Optional[float] = None) -> np.ndarray:
        handles = [self.submit(i) for i in np.asarray(node_ids).reshape(-1)]
        if not handles:
            return np.zeros((0, self.out_dim), np.float32)
        if not self._running:
            while any(not h.done() for h in handles) and self._drainable():
                self.flush()
        return np.stack([h.result(timeout) for h in handles])

    # -- flush policy ------------------------------------------------------

    def should_flush(self) -> bool:
        with self._lock:
            if not self._pending:
                return False
            if len(self._pending) >= self.config.max_batch:
                return True
            oldest = next(iter(self._pending.values())).enqueue_t
            return (self._clock() - oldest) * 1e3 >= self.config.max_delay_ms

    def pump(self) -> int:
        return self.flush() if self.should_flush() else 0

    # -- the three router stages ------------------------------------------

    def _assemble(self) -> Optional[_RoutedFlush]:
        """Drain + publish (mirrors `ServeEngine._assemble`): the owner
        split waits for `_seal_assembled` so late-admitted seeds route with
        their flush."""
        with self._lock:
            if not self._pending:
                return None
            keys = weighted_drain_keys(
                self._pending, self.config.max_batch,
                self.config.tenant_weights,
            )
            slots = [self._pending.pop(k) for k in keys]
            for s in slots:
                n = self._pending_tenant.get(s.tenant, 1) - 1
                if n > 0:
                    self._pending_tenant[s.tenant] = n
                else:
                    self._pending_tenant.pop(s.tenant, None)
            self._inflight.update(zip(keys, slots))
            fl = _RoutedFlush(keys, slots, [])
            fl.bucket = self.config.max_batch
            self._inflight_flushes += 1
            self.stats.inflight_peak = max(
                self.stats.inflight_peak, self._inflight_flushes
            )
            # caller holds _seq: the index _seal_assembled will draw. The
            # fid is stamped UNCONDITIONALLY since round 15 — the fault
            # injector and the ejection state machine key off it, not
            # just the journal
            fl.fid = self._flush_index + 1
            jr = self.journal
            if jr.enabled:
                for k, slot in zip(keys, slots):
                    jr.emit("assemble", slot.rid, fl.fid, k)
                jr.emit("flush", -1, fl.fid, len(keys), fl.bucket)
            if self.config.late_admission and len(keys) < fl.bucket:
                self._open = fl
        return fl

    def _seal_assembled(self, fl: _RoutedFlush) -> None:
        with self._lock:
            self._open = None
        self._flush_index += 1
        if self.workload is not None:
            # decay tick on the router's dispatch index (caller holds
            # _seq) — replay-deterministic, never wall time
            self.workload.tick()
        self.journal.emit("seal", -1, fl.fid, len(fl.keys), fl.bucket)
        try:
            arr = np.asarray(fl.keys, np.int64)
            owners = self.global2host[arr].astype(np.int64)
            rep = self.replica  # swapped only under the fence: stable here
            if rep is not None and rep.ids.size:
                # hot-set replication: replicated seeds re-route to the
                # LOCAL replica pseudo-owner — they never enter the serve
                # exchange (the whole point of the replica)
                owners = np.where(np.isin(arr, rep.ids), REPLICA_HOST,
                                  owners)
                pos = np.nonzero(owners == REPLICA_HOST)[0]
                if pos.size:
                    fl.split.append((REPLICA_HOST, arr[pos], pos))
            for h in range(self.hosts):
                pos = np.nonzero(owners == h)[0]
                if pos.size:
                    fl.split.append((h, arr[pos], pos))
            if self.config.record_dispatches:
                self.dispatch_log.append(
                    (arr.copy(), [(h, ids.copy()) for h, ids, _ in fl.split])
                )
        except BaseException as exc:
            fl.error = exc

    def _dispatch(self, fl: _RoutedFlush) -> Optional[np.ndarray]:
        """Forward the per-owner sub-batches and re-interleave the answers
        into flush-key order. Collective mode ships ids/logits over the
        mesh; host mode calls the owner engines directly — per-owner legs
        there carry the round-15 fault-injection hook, the
        ``hedge_deadline_ms`` deadline, and the failover re-route, and an
        owner failure lands in ``fl.slot_errors`` (that sub-batch's slots
        only), never in ``fl.error``. Replica legs (host `REPLICA_HOST`)
        are answered locally in BOTH modes and never touch the
        exchange."""
        # a = bucket per the EVENT_KINDS vocabulary; the router's "bucket"
        # is its admission cap (it pads nothing)
        self.journal.emit("dispatch", -1, fl.fid, fl.bucket)
        wl = self.workload
        out = np.zeros((len(fl.keys), self.out_dim), np.float32)
        owner_split = []
        for h, ids, pos in fl.split:
            if h == REPLICA_HOST:
                self._replica_leg(fl, ids, pos, out)
            else:
                owner_split.append((h, ids, pos))
        if self.exchange_mode == "collective":
            by_host = {h: (ids, pos) for h, ids, pos in owner_split}
            if by_host:  # an all-replica flush skips the collective whole
                host2ids = [
                    by_host[h][0] if h in by_host else np.array([], np.int64)
                    for h in range(self.hosts)
                ]
                t_x0 = self._clock() if wl is not None else 0.0
                try:
                    res = self.comm.exchange_serve(
                        host2ids, out_dim=self.out_dim, budget=self._budget
                    )
                except comm_mod.OwnerAnswerError as exc:
                    # the collective is one launch: it cannot fail
                    # per-owner, but the failure IS attributable — feed
                    # the health/ejection state before the whole-flush
                    # error propagates
                    self._owner_failed(exc.host, fl.fid)
                    raise
                if wl is not None:
                    # one exchange round-trip covers every owner: its
                    # duration is each participating owner's flush latency
                    # at the router grain (per-owner separation needs host
                    # mode or the owners' own monitors)
                    dt = self._clock() - t_x0
                    for h, ids, _ in owner_split:
                        wl.observe_flush(h, len(ids), dt)
                L = self._budget
                with self._lock:
                    self.stats.exchange_id_bytes += (
                        self.hosts * self.hosts * L * 4
                    )
                    self.stats.exchange_logit_bytes += (
                        self.hosts * self.hosts * L * self.out_dim * 4
                    )
                for h, (ids, pos) in by_host.items():
                    out[pos] = res[h]
                # a successful exchange is a successful leg for every
                # participating owner: reset their failure counts, so
                # `fails` stays CONSECUTIVE (not cumulative over days)
                # and a past ejection never latches in collective mode
                for h, _, _ in owner_split:
                    self._owner_ok(h)
        else:
            for h, ids, pos in owner_split:
                self._owner_leg(fl, h, ids, pos, out)
        out.setflags(write=False)
        # one routed round-trip = one "execute" at the router grain
        self.journal.emit("execute_done", -1, fl.fid, len(fl.split))
        return out

    # -- round-15 dispatch legs: replica, hedged owner, failover -----------

    def _replica_leg(self, fl: _RoutedFlush, ids, pos, out) -> None:
        """Serve a replicated sub-batch from the LOCAL hot-set replica —
        no routing, no exchange bytes. A (should-be-impossible) local
        failure takes the same failover path as an owner failure."""
        wl = self.workload
        t0 = self._clock()
        try:
            rows = np.asarray(self.replica.engine.predict(ids))
        except BaseException as exc:
            self._failover(fl, REPLICA_HOST, ids, pos, out, "error", exc)
            return
        if wl is not None:
            wl.observe_flush(REPLICA_HOST, len(ids), self._clock() - t0)
        out[pos] = rows
        with self._lock:
            self.stats.replica_hits += len(ids)

    def _owner_leg(self, fl: _RoutedFlush, h: int, ids, pos, out) -> None:
        """One host-mode owner sub-batch: fault-injection hook, optional
        per-owner deadline, failover on timeout/error/ejection. Success
        resets the owner's health; failure feeds the ejection state
        machine (flush-indexed backoff — deterministic under replay)."""
        wl = self.workload
        deadline_s = self.config.hedge_deadline_ms / 1e3
        # honoring an ejection only makes sense when someone else can
        # serve the sub-batch: with no failover target, skipping the
        # owner would CONVERT its traffic into guaranteed errors for the
        # whole backoff window — attempt it instead
        ejected = (self._has_failover(h, ids)
                   and self._owner_ejected(h, fl.fid))
        rows, err, timed_out = None, None, False
        if not ejected:
            t0 = self._clock()
            try:
                if deadline_s > 0:
                    # the fault hook runs INSIDE the supervised leg so a
                    # stalled owner is indistinguishable from a slow one
                    # — exactly what the deadline exists to catch
                    rows, timed_out = self._call_with_deadline(
                        h, ids, deadline_s, fl.fid
                    )
                    if timed_out:
                        err = OwnerTimeout(
                            f"owner {h} missed the "
                            f"{self.config.hedge_deadline_ms} ms hedge "
                            f"deadline at dispatch index {fl.fid}"
                        )
                else:
                    if self.faults is not None:
                        self.faults.check(h, fl.fid)
                    rows = np.asarray(self.engines[h].predict(ids))
            except BaseException as exc:
                err = exc
            if wl is not None:
                # host mode calls owners sequentially, so each owner's
                # leg is individually timed — TRUE per-owner straggler
                # evidence. A timed-out leg is CENSORED at the deadline
                # (the owner did NOT answer in the measured wall; the
                # wedged-owner fast path would otherwise record ~0 ms
                # and rank the slowest owner fastest)
                dt = self._clock() - t0
                if timed_out:
                    dt = max(dt, deadline_s)
                wl.observe_flush(h, len(ids), dt)
        if rows is not None and err is None:
            self._owner_ok(h)
            out[pos] = rows
            return
        if not ejected:
            self._owner_failed(h, fl.fid)
        reason = ("ejected" if ejected
                  else "timeout" if timed_out else "error")
        self._failover(fl, h, ids, pos, out, reason, err)

    def _call_with_deadline(self, h: int, ids, deadline_s: float,
                            fid: int):
        """Run an owner leg (fault hook included) on a worker thread
        with a deadline. On timeout the worker is ABANDONED (its eventual
        answer lands in a local box nobody reads — never the flush's
        output) and the caller hedges; an in-leg exception re-raises
        here. While ANY abandoned leg to an owner is still alive, further
        legs to it time out immediately instead of stacking more blocked
        threads — at most ``max_in_flight`` concurrent checks can slip
        through per wedge episode, so thread growth is bounded."""
        with self._lock:
            legs = self._abandoned_legs.get(h, [])
            legs[:] = [t for t in legs if t.is_alive()]
            if legs:
                return None, True  # owner still wedged from earlier legs
        box: Dict[str, object] = {}
        engine = self.engines[h]

        def run():
            try:
                if self.faults is not None:
                    self.faults.check(h, fid)
                box["rows"] = np.asarray(engine.predict(ids))
            except BaseException as exc:  # delivered to the caller below
                box["err"] = exc

        th = threading.Thread(target=run, daemon=True,
                              name="quiver-hedged-owner-leg")
        th.start()
        th.join(deadline_s)
        if th.is_alive():
            with self._lock:
                self._abandoned_legs.setdefault(h, []).append(th)
            return None, True
        if "err" in box:
            raise box["err"]
        return box["rows"], False

    def _pick_failover(self, h: int, ids
                       ) -> Tuple[Optional[ServeEngine], str]:
        """THE failover target-selection rule, used by both the ejection
        honor decision and the re-route itself (one copy — if they
        disagreed, an ejected owner could be skipped with no target and
        its sub-batch error needlessly): the full-graph fallback serves
        anything; the replica only sub-batches fully inside the hot
        set."""
        if self.fallback is not None:
            return self.fallback, "fallback"
        rep = self.replica
        if (rep is not None and h != REPLICA_HOST
                and all(int(x) in rep.id_set for x in ids)):
            return rep.engine, "replica"
        return None, ""

    def _has_failover(self, h: int, ids) -> bool:
        return self._pick_failover(h, ids)[0] is not None

    def _failover(self, fl: _RoutedFlush, h: int, ids, pos, out,
                  reason: str, err: Optional[BaseException]) -> None:
        """Re-route a failed sub-batch: the full-graph fallback serves
        anything; the replica serves sub-batches fully inside the hot
        set. No (working) target -> the sub-batch's OWN slots resolve
        with the error (per-request isolation — the flush, the engine,
        and every other sub-batch keep serving). Every decision lands in
        the hedge log keyed by the dispatch index."""
        target, tname = self._pick_failover(h, ids)
        if target is not None:
            try:
                rows = np.asarray(target.predict(ids))
                out[pos] = rows
                with self._lock:
                    self.stats.hedges += 1
                    self.stats.hedged_seeds += len(ids)
                    if reason == "timeout":
                        self.stats.hedge_timeouts += 1
                    elif reason == "ejected":
                        self.stats.hedge_ejected += 1
                    else:
                        self.stats.hedge_errors += 1
                self.hedge_log.append((fl.fid, int(h), reason, tname))
                self.journal.emit("hedge", -1, fl.fid, h)
                return
            except BaseException as exc:
                err = exc
        with self._lock:
            self.stats.hedge_failed += 1
        self.hedge_log.append((fl.fid, int(h), reason, "none"))
        final = err if err is not None else RuntimeError(
            f"owner {h} unavailable ({reason}) and no failover target"
        )
        for p in pos:
            fl.slot_errors[int(p)] = final

    # -- owner health / ejection state (flush-indexed, replay-stable) ------

    def _owner_ejected(self, h: int, fid: int) -> bool:
        with self._lock:
            st = self._owner_health.get(h)
            if st is None or st["ejected_at"] < 0:
                return False
            if fid >= st["ejected_at"] + self.config.eject_backoff_flushes:
                st["ejected_at"] = -1  # backoff expired: half-open probe
                return False
            return True

    def _owner_failed(self, h: int, fid: int) -> None:
        with self._lock:
            st = self._owner_health.setdefault(
                h, {"fails": 0, "ejected_at": -1}
            )
            st["fails"] += 1
            if st["fails"] >= self.config.eject_after and st["ejected_at"] < 0:
                st["ejected_at"] = fid
                self.stats.owner_ejections += 1
                self.journal.emit("eject", -1, fid, h)

    def _owner_ok(self, h: int) -> None:
        with self._lock:
            st = self._owner_health.get(h)
            if st is not None:
                st["fails"] = 0
                st["ejected_at"] = -1

    def owner_health(self) -> Dict[int, Dict[str, int]]:
        """Per-owner hedging health snapshot: consecutive ``fails`` and
        ``ejected_at`` (the dispatch index an ejection started at; -1 =
        serving)."""
        with self._lock:
            return {h: dict(st)
                    for h, st in sorted(self._owner_health.items())}

    def hedge_events(self) -> List[Tuple[int, int, str, str]]:
        """The hedge log sorted by (dispatch index, owner, reason,
        target) — the deterministic replay view (append order may
        interleave across concurrent in-flight flushes)."""
        return sorted(self.hedge_log)

    def _resolve(self, fl: _RoutedFlush, rows: Optional[np.ndarray]) -> None:
        """Per-request error isolation (round 15): a slot resolves with
        ITS error — ``fl.error`` (whole-flush: assemble/collective
        failure) or its position's ``fl.slot_errors`` entry (its owner
        sub-batch failed with no failover) — and every other slot
        resolves normally. An errored slot is never cached."""
        with self._lock:
            now = t_res0 = self._clock()
            for i, (k, slot) in enumerate(zip(fl.keys, fl.slots)):
                self._inflight.pop(k, None)
                if slot.event.is_set():
                    # abandoned by a bounded stop() drain (resolve-once
                    # rule — see ServeEngine._resolve)
                    continue
                err = fl.error or fl.slot_errors.get(i)
                if err is None:
                    if slot.version == self.params_version:
                        self.cache.put(k, slot.version, rows[i])
                    slot.resolve(rows[i])
                else:
                    slot.resolve(None, error=err)
                    self.stats.request_errors += 1
                for t0, tenant in slot.waiters:
                    ms = (now - t0) * 1e3
                    self.stats.latency.record_ms(ms)
                    self.stats.tenant_hist(tenant).record_ms(ms)
            if fl.error is None:
                self.stats.router_dispatches += 1
                self.stats.routed_seeds += len(fl.keys)
                for h, ids, _ in fl.split:
                    self.stats.sub_batches[h] = self.stats.sub_batches.get(h, 0) + 1
                    self.stats.sub_batch_seeds[h] = (
                        self.stats.sub_batch_seeds.get(h, 0) + len(ids)
                    )
            self._inflight_flushes -= 1
            self._fence.notify_all()
            self.stats.spans.record("resolve", t_res0, self._clock())
            self.journal.emit("resolve", -1, fl.fid, len(fl.keys))

    def flush(self) -> int:
        """Route up to ``max_batch`` pending unique seeds NOW. Synchronous
        on the calling thread; up to ``max_in_flight`` concurrent callers
        overlap (the router's assemble/split is serialized in dispatch
        order under ``_seq``, so the router log — and through it every
        shard's key stream — stays deterministic). As in
        `ServeEngine.flush`, the window permit is taken under ``_seq``
        AFTER the drain, so seeds arriving while this flush waits for a
        slot join it (late admission) before the owner split is sealed.

        ERROR CONTRACT (round 15): an owner sub-batch failure in host
        mode is PER-REQUEST — it resolves only that sub-batch's slots
        with the exception (after failover was tried) and `flush` returns
        normally; only whole-flush infrastructure failures (assemble/seal
        errors, a collective-exchange abort) re-raise here."""
        fl = None
        have_permit = False
        try:
            with self._seq:
                t0 = self._clock()
                fl = self._assemble()
                if fl is not None:
                    self.stats.spans.record("assemble", t0, self._clock())
                if fl is None:
                    return 0
                try:
                    jr = self.journal
                    t_w0 = self._clock() if jr.enabled else 0.0
                    self._window.acquire()
                    have_permit = True
                    if jr.enabled:
                        jr.emit("window_wait", -1, fl.fid,
                                self._clock() - t_w0)
                    t0 = self._clock()
                    self._seal_assembled(fl)
                    self.stats.spans.record("assemble", t0, self._clock())
                finally:
                    # _seal_assembled's first act already closed admission
                    # (it MUST happen under _lock before the key draw);
                    # this repeat only covers an interrupt landing between
                    # the window acquire and the seal
                    with self._lock:
                        self._open = None
            rows = None
            if fl.error is None:
                t0 = self._clock()
                try:
                    rows = self._dispatch(fl)
                except BaseException as exc:
                    fl.error = exc
                self.stats.spans.record("dispatch", t0, self._clock())
            self._resolve(fl, rows)
            if fl.error is not None:
                raise fl.error
            return len(fl.keys)
        finally:
            if have_permit:
                self._window.release()

    def _drainable(self) -> bool:
        with self._lock:
            return bool(self._pending)

    # -- weight updates / warmup / lifecycle -------------------------------

    def update_params(self, params) -> None:
        """Fence the ROUTER (no routed flush in the air), then fence every
        shard engine through its own `update_params` — so no served logit
        anywhere crosses the weight update, and every shard's embedding
        cache is invalidated together."""
        with self._seq:
            with self._fence:
                while self._inflight_flushes:
                    self._fence.wait()
                for eng in self.engines.values():
                    eng.update_params(params)
                # the hot-set replica and the full-graph fallback serve
                # under the same weights as the owners — same fence
                if self.replica is not None:
                    self.replica.engine.update_params(params)
                if self.fallback is not None:
                    self.fallback.update_params(params)
                self._params = params
                self.params_version += 1
                self.cache.invalidate()
                for slot in self._pending.values():
                    slot.version = self.params_version

    def adapt_tiers(self) -> Dict[int, Dict[str, object]]:
        """One fleet-wide promote/demote pass (round 14): fence the
        ROUTER (no routed flush in the air — the same drain as
        `update_params`), then run each owner engine's `adapt_tiers`
        under it; every owner fences its own in-flight flushes too, so
        no flush anywhere straddles a placement batch. Owners whose
        feature has no adaptive store (or no workload sketch) are
        skipped. Per-owner summaries keyed by host, deterministic order.
        NOTE the owner engines' own background consumers stay OFF in
        dist mode (``tier_adapt_every_s`` is not inherited by the shard
        config) — the router is the single adaptation driver, which is
        what keeps fleet passes fenced against routed flushes."""
        out: Dict[int, Dict[str, object]] = {}
        with self._seq:
            with self._fence:
                while self._inflight_flushes:
                    self._fence.wait()
                for h in sorted(self.engines):
                    eng = self.engines[h]
                    if eng._tier_feature is None or eng.workload is None:
                        continue
                    out[h] = eng.adapt_tiers()
        return out

    @property
    def placement_version(self) -> int:
        """Sum of the owner engines' fenced placement batches (a fleet
        placement-progress gauge, not a coherence version — shards move
        rows independently)."""
        return sum(e.placement_version for e in self.engines.values())

    def refresh_replicas(self, ids=None, k: Optional[int] = None,
                         ) -> Dict[str, object]:
        """(Re)build the hot-set replica (round 15, ROADMAP item 3a):
        pick the head — ``ids`` explicitly, or the ``k`` hottest seeds
        from the ROUTER's workload sketch (``k`` defaults to
        ``config.replicate_top_k``; price it with `scaling.skew_table`
        from the measured head-concentration curve) — and mirror it
        locally as a full `ServeEngine` over the head's halo-closure
        topology (`shard_topology_for_seeds`) + feature rows
        (`ClosureFeature`).

        The swap runs under the SAME fence as `update_params` /
        `apply_placement` (sequencing lock + in-flight drain), so no
        routed flush ever straddles a replica version; the router cache
        entries of every REFRESHED key (old set union new set — the keys
        whose serving path changed) are invalidated, and exactly those
        (pinned in tests/test_serve_dist.py). ``replica_version`` bumps
        per refresh. ``ids=[]`` disables replication.

        Replica-served rows keep the standing parity contract: the
        closure topology makes the replica sampler's draws for
        replicated seeds bit-equal to a full-graph sampler's on the same
        key stream, so `replay_fleet_oracle` replays its dispatch log
        exactly like an owner shard's."""
        if self._replica_materials is None:
            raise ValueError(
                "hot-set replication needs the build()-time materials "
                "(full topology + feature table); a bare-constructed "
                "multi-process engine holds only its own shard"
            )
        m = self._replica_materials
        if ids is None:
            k = int(self.config.replicate_top_k if k is None else k)
            if k <= 0:
                raise ValueError(
                    "pass ids= or set DistServeConfig.replicate_top_k > 0"
                )
            if self.workload is None:
                raise ValueError(
                    "picking the hot set reads the router workload sketch "
                    "— pass DistServeConfig(workload=WorkloadConfig(...)) "
                    "or give ids= explicitly"
                )
            ids = self.workload.hot_set(k)
        ids = np.unique(np.asarray(ids, np.int64))
        new_replica = None
        st: Dict[str, float] = {}
        if ids.size:
            from ..pyg.sage_sampler import GraphSageSampler

            sizes = list(m["sizes"])
            # adjacency closure: len(sizes)-1 expansion hops; feature
            # closure one deeper (leaves gathered, never expanded) — the
            # same construction as the owner shards in `build`
            topo_r, st, closure_ids = shard_topology_for_seeds(
                m["csr_topo"], ids, hops=len(sizes) - 1,
                closure_hops=len(sizes),
            )
            sampler = GraphSageSampler(
                topo_r, sizes=sizes, mode=m["sampler_mode"],
                seed=m["sampler_seed"], **m["sampler_kw"],
            )
            n = m["csr_topo"].indptr.shape[0] - 1
            local_map = np.full(n, -1, np.int32)
            local_map[closure_ids] = np.arange(
                closure_ids.shape[0], dtype=np.int32
            )
            feat_r = ClosureFeature(
                np.asarray(m["feat"], np.float32)[closure_ids], local_map
            )
        # construct + AOT-warmup the replica engine OUTSIDE the fence:
        # the bucket compiles take seconds, and a routine refresh must
        # not stall every submit() (the fence Condition wraps the
        # router's request lock) for that long. Only the pointer swap +
        # cache invalidation need the fence.
        eng = None
        if ids.size:
            with self._lock:
                params_snapshot = self._params
            eng = ServeEngine(
                m["model"], params_snapshot, sampler, feat_r,
                m["shard_config"],
            )
            eng.warmup()
        with self._seq:
            with self._fence:
                while self._inflight_flushes:
                    self._fence.wait()
                if eng is not None and self._params is not params_snapshot:
                    # a weight update landed while we compiled: re-stamp
                    # under the fence (cheap — swap + invalidate) so the
                    # replica never serves stale params
                    eng.update_params(self._params)
                old = self.replica
                if old is not None and old.engine.config.record_dispatches:
                    # kept ONLY for the replay oracle (its dispatch log
                    # vouches for pre-refresh rows) — a production engine
                    # without dispatch recording retains nothing, so
                    # periodic refreshes never accumulate dead engines
                    self._retired_replicas.append(old.engine)
                self.replica_version += 1
                if eng is not None:
                    new_replica = _HotReplica(
                        eng, ids, self.replica_version, dict(st)
                    )
                self.replica = new_replica
                old_ids = old.ids if old is not None else np.array(
                    [], np.int64
                )
                refreshed = np.union1d(old_ids, ids)
                invalidated = self.cache.invalidate_keys(
                    int(x) for x in refreshed
                )
        return {
            "replicated": int(ids.size),
            "version": self.replica_version,
            "invalidated": invalidated,
            "closure_nodes": int(st.get("closure_nodes", 0)),
            "edge_frac": float(st.get("edge_frac", 0.0)),
        }

    def warmup(self) -> Dict[object, Dict[int, float]]:
        """Pre-trace every shard engine's bucket programs (twin samplers
        where supported, so no shard's key stream moves) — plus the
        full-graph fallback's and the live replica's, under the
        ``"fallback"`` / ``"replica"`` keys. Returns
        {host: {bucket: seconds}}."""
        out: Dict[object, Dict[int, float]] = {
            h: eng.warmup() for h, eng in self.engines.items()
        }
        if self.fallback is not None:
            out["fallback"] = self.fallback.warmup()
        if self.replica is not None:
            out["replica"] = self.replica.engine.warmup()
        return out

    def aggregate_stats(self) -> Dict[str, object]:
        """Router snapshot + the per-shard `ServeStats` merged into one
        view (`ServeStats.merge` -> the `trace` merge family) + per-shard
        topology shard stats. The merged latency histogram is OWNER-side
        latency; end-to-end latency (queue + route + owner + return) is the
        router's own ``stats.latency``. The replica/fallback engines (when
        built) merge into ``shards_merged`` and appear under their own
        keys — they are serving engines like any owner."""
        merged = ServeStats()
        for h in sorted(self.engines):
            merged.merge(self.engines[h].stats)
        out: Dict[str, object] = {
            "router": self.stats.snapshot(),
            "per_shard": {
                h: self.engines[h].stats.snapshot() for h in sorted(self.engines)
            },
            "topology": self.shard_topo_stats,
        }
        if self.replica is not None:
            merged.merge(self.replica.engine.stats)
            out["replica"] = self.replica.engine.stats.snapshot()
            out["replica"]["replicated_ids"] = int(self.replica.ids.size)
        if self.fallback is not None:
            merged.merge(self.fallback.stats)
            out["fallback"] = self.fallback.stats.snapshot()
        out["shards_merged"] = merged.snapshot()
        return out

    def reset_stats(self) -> None:
        """Zero router counters (re-pointing the router cache's counter at
        the fresh stats, same contract as `ServeEngine.reset_stats`) and
        every shard engine's stats (journals included). Cache CONTENTS are
        untouched."""
        with self._lock:
            self.stats = DistServeStats()
            self.cache.counters = self.stats.router_cache
            if self.journal.enabled:
                self.journal.clear()
            if self.workload is not None:
                self.workload.clear()
        for eng in self.engines.values():
            eng.reset_stats()
        if self.replica is not None:
            self.replica.engine.reset_stats()
        if self.fallback is not None:
            self.fallback.reset_stats()

    # -- fleet observability ----------------------------------------------

    def register_metrics(self, registry: Optional[MetricsRegistry] = None,
                         prefix: str = "quiver_router",
                         labels: Optional[Dict[str, str]] = None,
                         ) -> MetricsRegistry:
        """Adapt the ROUTER's live state into a registry (created when not
        given): `DistServeStats` counters, queue/window gauges, exchange
        wire bytes, per-owner sub-batch counters (``host`` label), the
        router result cache, and the end-to-end latency histogram. All
        callback-backed (read at exposition time, `reset_stats`-safe).
        Owner-engine metrics ride :meth:`fleet_registry`."""
        reg = registry if registry is not None else MetricsRegistry()
        for f in ("requests", "coalesced", "router_dispatches",
                  "routed_seeds", "late_admitted", "replica_hits",
                  "hedges", "hedged_seeds", "hedge_timeouts",
                  "hedge_errors", "hedge_ejected", "hedge_failed",
                  "owner_ejections", "shed", "request_errors",
                  "undrained"):
            reg.counter_fn(f"{prefix}_{f}_total",
                           (lambda f=f: getattr(self.stats, f)),
                           f"DistServeStats.{f}", labels)
        reg.gauge_fn(f"{prefix}_replica_version",
                     lambda: self.replica_version,
                     "hot-set replica refreshes applied", labels)
        reg.gauge_fn(f"{prefix}_replica_rows",
                     lambda: (self.replica.ids.size
                              if self.replica is not None else 0),
                     "seeds currently replicated on every host", labels)
        reg.gauge_fn(f"{prefix}_owners_ejected",
                     lambda: sum(
                         1 for st in self.owner_health().values()
                         if st["ejected_at"] >= 0
                     ),
                     "owners currently in ejection backoff", labels)
        register_tenant_latency(
            reg, prefix, "end-to-end routed latency by submitting tenant",
            lambda: self.stats, self.config.tenant_weights, labels,
        )
        reg.counter_fn(f"{prefix}_exchange_id_bytes_total",
                       lambda: self.stats.exchange_id_bytes,
                       "global collective id payload bytes", labels)
        reg.counter_fn(f"{prefix}_exchange_logit_bytes_total",
                       lambda: self.stats.exchange_logit_bytes,
                       "global collective logits payload bytes", labels)
        reg.gauge_fn(f"{prefix}_pending_depth", lambda: len(self._pending),
                     "unique seeds queued at the router", labels)
        reg.gauge_fn(f"{prefix}_inflight_flushes",
                     lambda: self._inflight_flushes,
                     "routed flushes between assemble and resolve", labels)
        reg.gauge_fn(f"{prefix}_inflight_window",
                     lambda: self.config.max_in_flight,
                     "configured router max_in_flight bound", labels)
        reg.gauge_fn(f"{prefix}_inflight_peak",
                     lambda: self.stats.inflight_peak,
                     "largest routed in-flight occupancy observed", labels)
        reg.gauge_fn(f"{prefix}_cache_rows", lambda: len(self.cache),
                     "router result-cache resident rows", labels)
        reg.gauge_fn(f"{prefix}_params_version", lambda: self.params_version,
                     "current weights version", labels)
        reg.gauge_fn(f"{prefix}_placement_version",
                     lambda: self.placement_version,
                     "fenced tier-placement batches across the fleet",
                     labels)
        reg.gauge_fn(f"{prefix}_tier_adapt_errors",
                     lambda: self.tier_adapt_errors,
                     "failed fleet tier-adaptation passes", labels)
        for h in sorted(self.engines):
            reg.counter_fn(
                f"{prefix}_sub_batches_total",
                (lambda h=h: self.stats.sub_batches.get(h, 0)),
                "owner sub-batches routed",
                dict(labels or {}, host=str(h)),
            )
            reg.counter_fn(
                f"{prefix}_sub_batch_seeds_total",
                (lambda h=h: self.stats.sub_batch_seeds.get(h, 0)),
                "seeds routed to owner",
                dict(labels or {}, host=str(h)),
            )
        register_hit_rate(reg, f"{prefix}_cache",
                          lambda: self.stats.router_cache, labels)
        reg.histogram(f"{prefix}_latency_ms",
                      "end-to-end routed request latency", labels,
                      fn=lambda: self.stats.latency)
        if self.workload is not None:
            self.workload.register_metrics(
                reg, prefix=f"{prefix}_workload", labels=labels,
                owners=range(self.hosts),
            )
        return reg

    def fleet_registry(self, registry: Optional[MetricsRegistry] = None,
                       ) -> MetricsRegistry:
        """ONE registry over the whole fleet: the router's metrics plus
        every owner engine's (`ServeEngine.register_metrics`) under a
        ``host`` label, registered in sorted-host order — the same
        deterministic merge discipline as `aggregate_stats`, so two
        expositions of the same state are textually identical. With no
        ``registry`` argument the engine's CACHED fleet registry is
        returned (adapters are callback-backed readers, so one registry
        serves every scrape; re-registration re-points, never
        duplicates)."""
        if registry is None:
            if getattr(self, "_fleet_reg", None) is None:
                self._fleet_reg = MetricsRegistry()
            registry = self._fleet_reg
        reg = self.register_metrics(registry)
        for h in sorted(self.engines):
            self.engines[h].register_metrics(
                reg, prefix="quiver_serve", labels={"host": str(h)}
            )
        # the replica/fallback engines are serving engines like any owner
        # — same families under reserved host labels. A replica refresh
        # swaps the engine; re-calling fleet_registry re-points the
        # adapters (last-writer-wins, the registry's documented rule).
        if self.replica is not None:
            self.replica.engine.register_metrics(
                reg, prefix="quiver_serve", labels={"host": "replica"}
            )
        if self.fallback is not None:
            self.fallback.register_metrics(
                reg, prefix="quiver_serve", labels={"host": "fallback"}
            )
        return reg

    def aggregate_journal(self) -> List[Tuple]:
        """The fleet's lifecycle events as (host, t, kind, rid, fid, a, b)
        tuples — router events first under host=-1, then each owner's in
        sorted-host order. Within one journal the ring is already in
        emit order, and flush events emit in dispatch-index order (seals
        are serialized under each engine's sequencing lock), so the merge
        is deterministic for a deterministic run — the same contract as
        the dispatch-log/stats merges."""
        merged: List[Tuple] = [(-1, *ev) for ev in self.journal.snapshot()]
        for h in sorted(self.engines):
            merged.extend(
                (h, *ev) for ev in self.engines[h].journal.snapshot()
            )
        return merged

    def fleet_snapshot(self) -> Dict[str, object]:
        """Fleet observability in one JSON-able document: the router's
        request breakdown (end-to-end stages), per-owner breakdowns
        (sorted hosts), and the fleet registry snapshot. This is the
        serve-stack answer to "where did this request's time go" at fleet
        grain — queue/route at the router, device/resolve at the owners."""
        return {
            "router": self.journal.request_breakdown(),
            "per_shard": {
                h: self.engines[h].journal.request_breakdown()
                for h in sorted(self.engines)
            },
            "metrics": self.fleet_registry().snapshot(),
        }

    def workload_report(self, capacities: Sequence[int] = (),
                        ) -> Dict[str, object]:
        """The fleet's skew/imbalance planning document (round 13;
        requires ``DistServeConfig.workload``):

        - ``router`` — the ROUTER monitor's `skew_report`: since the
          router observes every submitted seed, this is the fleet's
          access-frequency truth (head-concentration curve, predicted
          hit rate vs capacity) plus per-owner routed load, imbalance
          and straggler stats;
        - ``per_shard`` — each owner engine's own report (owner-side
          cache outcomes, tier attribution);
        - ``shards_merged`` — `WorkloadMonitor.merge_all` over the owner
          monitors in sorted-host order: the multi-process deployment
          shape, where no single router sees every seed and the fleet
          view IS the merge (order-independent by construction — pinned
          in tests/test_skew.py). NOT router + owners: the router
          already counted every seed the owners saw, and summing the two
          would double-count.
        """
        if self.workload is None:
            raise ValueError(
                "workload telemetry is off — pass "
                "DistServeConfig(workload=WorkloadConfig(...))"
            )
        owner_monitors = [
            self.engines[h].workload
            for h in sorted(self.engines)
            if self.engines[h].workload is not None
        ]
        out: Dict[str, object] = {
            "router": self.workload.skew_report(capacities=capacities),
            "per_shard": {
                str(h): self.engines[h].workload.skew_report(
                    capacities=capacities
                )
                for h in sorted(self.engines)
                if self.engines[h].workload is not None
            },
        }
        if owner_monitors:
            out["shards_merged"] = WorkloadMonitor.merge_all(
                owner_monitors
            ).skew_report(capacities=capacities)
        return out

    def export_chrome_trace(self, path: str, extra_sources: Sequence = (),
                            metadata: Optional[Dict[str, object]] = None,
                            ) -> Dict[str, object]:
        """One Perfetto-loadable timeline for the fleet: router spans +
        journal, every owner engine's spans + journal (sorted hosts), and —
        when `comm.record_exchange_spans` installed a recorder — the wire
        legs, all on the shared monotonic clock."""
        sources: List = [("router.spans", self.stats.spans)]
        if self.journal.enabled:
            sources.append(("router.journal", self.journal))
        if self.workload is not None and self.workload.counters is not None:
            sources.append(("router.workload", self.workload.counters))
        for h in sorted(self.engines):
            eng = self.engines[h]
            sources.append((f"owner{h}.spans", eng.stats.spans))
            if eng.journal.enabled:
                sources.append((f"owner{h}.journal", eng.journal))
            if eng.workload is not None and eng.workload.counters is not None:
                sources.append((f"owner{h}.workload", eng.workload.counters))
        rec = comm_mod.EXCHANGE_SPANS
        if rec is not None and len(rec):
            sources.append(("comm.exchange", rec))
        sources.extend(extra_sources)
        return _export_chrome_trace(path, sources, metadata)

    def start(self) -> "DistServeEngine":
        if self._running:
            return self
        self._running = True
        self._threads = [
            threading.Thread(
                target=self._poll_loop,
                name=f"quiver-dist-serve-flusher-{i}",
                daemon=True,
            )
            for i in range(self.config.max_in_flight)
        ]
        if self.config.tier_adapt_every_s > 0 and any(
            e._tier_feature is not None and e.workload is not None
            for e in self.engines.values()
        ):
            self._threads.append(
                threading.Thread(
                    target=self._tier_loop,
                    name="quiver-dist-serve-tiers",
                    daemon=True,
                )
            )
        for t in self._threads:
            t.start()
        return self

    def _tier_loop(self) -> None:
        from ..tiers import tier_daemon_loop

        tier_daemon_loop(self)

    def stop(self, drain: bool = True) -> None:
        """Stop the pollers and retire queued work, BOUNDED by
        ``config.drain_deadline_s`` (round 15): a poller or owner that
        died mid-flush must not hang the caller. Work not retired by the
        deadline resolves with `serve.engine.DrainTimeout` and is counted
        in ``stats.undrained`` — in the snapshot, never silently
        dropped."""
        self._running = False
        # one deadline covers poller joins too (a poller wedged mid-flush
        # must not defeat the bound — see ServeEngine.stop)
        deadline = self._clock() + self.config.drain_deadline_s
        for t in self._threads:
            t.join(timeout=max(deadline - self._clock(), 0.05))
        self._threads = []
        if drain:
            while self._drainable() and self._clock() < deadline:
                try:
                    self.flush()
                except Exception:
                    pass  # the failing flush resolved its own waiters
        with self._fence:
            while self._inflight_flushes and self._clock() < deadline:
                self._fence.wait(timeout=0.05)
        abandon_undrained(self, drained=drain)

    def _poll_loop(self) -> None:
        while self._running:
            try:
                self.pump()
            except Exception:
                # whole-flush infrastructure errors only (round-15
                # contract: owner failures are per-request and never
                # raise out of flush); the failing flush already resolved
                # its waiters with the error — keep serving
                pass
            time.sleep(self.config.flush_poll_ms / 1e3)

    def __enter__(self) -> "DistServeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def replay_shard_oracle(
    dist: DistServeEngine,
    model,
    params,
    full_sampler_factory: Callable[[], object],
    full_feature,
) -> Dict[int, np.ndarray]:
    """THE parity oracle: replay every shard engine's dispatch log through
    a FRESH sampler over the FULL graph (`full_sampler_factory` must birth
    it exactly like the shard samplers — same seed — so its key stream
    matches) and the offline `inference.batch_logits` path over the full
    feature table. Returns {node_id: logits row} for the first computation
    of each node per shard.

    That this oracle uses the FULL topology + FULL features is the point:
    it proves a shard served from 1/H of each table produced logits
    bit-identical to single-host offline eval. Shard engines must have
    been built with ``record_dispatches=True`` (`DistServeConfig` default
    shard config inherits the router's flag)."""
    from ..inference import _cached_apply, batch_logits

    apply = _cached_apply(model)
    served: Dict[int, np.ndarray] = {}
    for h in sorted(dist.engines):
        sampler = full_sampler_factory()
        for padded, nvalid in dist.engines[h].dispatch_log:
            logits = np.asarray(
                batch_logits(apply, params, sampler, full_feature, padded)
            )
            for i in range(nvalid):
                served.setdefault(int(padded[i]), logits[i])
    return served


def replay_fleet_oracle(
    dist: DistServeEngine,
    model,
    params,
    full_sampler_factory: Callable[[], object],
    full_feature,
) -> Dict[int, List[np.ndarray]]:
    """`replay_shard_oracle` extended over the WHOLE round-15 fleet:
    owners + the hot-set replica + the full-graph fallback, each engine's
    dispatch log replayed through a fresh FULL-graph sampler and the
    offline `batch_logits` path, collecting EVERY computation of every
    node (not just the first — a cache invalidation, e.g. a replica
    refresh, can legitimately recompute a node under a later key draw).

    Returns {node_id: [candidate rows]}. Under hedged/failover dispatch a
    node may be computed by more than one engine over a run (its owner
    before a fault, the fallback after) — a served row is CORRECT iff it
    bit-matches one candidate, which is exactly the fault-parity
    acceptance the probe and tests/test_faults.py assert: faults and
    failovers change WHO computes, never change any completed bit away
    from an offline full-graph replay."""
    from ..inference import _cached_apply, batch_logits

    apply = _cached_apply(model)
    engines: Dict[object, ServeEngine] = dict(dist.engines)
    if dist.replica is not None:
        engines["replica"] = dist.replica.engine
    for i, retired in enumerate(dist._retired_replicas):
        engines[f"replica_retired_{i}"] = retired
    if dist.fallback is not None:
        engines["fallback"] = dist.fallback
    served: Dict[int, List[np.ndarray]] = {}
    for h in sorted(engines, key=str):
        sampler = full_sampler_factory()
        for padded, nvalid in engines[h].dispatch_log:
            logits = np.asarray(
                batch_logits(apply, params, sampler, full_feature, padded)
            )
            for i in range(nvalid):
                served.setdefault(int(padded[i]), []).append(logits[i])
    return served
